"""Runtime concurrency sanitizer: instrumented locks + ownership checks.

The static lock-discipline pass proves field accesses are *lexically*
covered by a lock; this module closes the dynamic half of the story:

- ``SanitizedLock`` wraps a ``threading.RLock``/``Lock`` and records,
  per acquisition, the set of locks already held by the acquiring
  thread. Those (held -> acquired) edges form the process-wide
  lock-acquisition **order graph**; the moment an edge closes a cycle
  (thread A takes L1 then L2 while thread B takes L2 then L1 — a
  deadlock waiting for the right interleaving) a violation is recorded
  with both edges' stacks of lock names.
- It also tracks per-lock **hold times** (first acquire -> final
  release, recursion-aware), reporting the max per lock — the number
  that says whether an RPC handler is stalling the round pipeline.
- ``@requires_lock`` methods (core/locking.py) report an
  **unowned-access** violation when entered without the receiver's
  lock held.

Enabled by ``SWTPU_SANITIZE=1`` (any non-empty value other than "0").
The tier-1 conftest turns it on for every ``runtime``/``recovery``/
``faults``-marked test and asserts a clean report at teardown; in
production the wrapper is never installed (``maybe_wrap`` returns the
raw lock), so there is zero steady-state overhead.

Under ``SWTPU_SANITIZE_EXPLORE=<seed>`` (analysis/explorer.py) every
instrumented acquire/release additionally injects a seeded scheduling
perturbation, so N seeds exercise N deterministic-by-seed
interleavings of the same critical sections with all of the above
checks evaluated on each.

Two further knobs close the loop with the static lockflow analysis
(analysis/lockflow.py):

- ``SWTPU_SANITIZE_HOLD_MS=<ms>`` turns the hold-time telemetry into
  advisory warnings: any outermost hold at or above the threshold is
  recorded in ``report()["hold_warnings"]``. Unset (the default)
  keeps today's behavior; a garbage value logs once and stays off.
- ``SWTPU_SANITIZE_GRAPH_OUT=<path>`` dumps the cumulative observed
  lock-order graph as JSON at exit, in the same shape as the static
  ``static_lock_order_graph``. CI asserts the runtime edges are a
  subset of the static ones (``--assert-contains``), so a lock order
  the analyzer cannot see would fail the build rather than ship.

The wrapper deliberately implements the private RLock hooks
(``_is_owned`` / ``_release_save`` / ``_acquire_restore``) so a
``threading.Condition`` built on it — the scheduler's ``self._cv`` —
routes ``wait()``'s full release/reacquire through the bookkeeping.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

from . import explorer


def enabled() -> bool:
    return os.environ.get("SWTPU_SANITIZE", "0") not in ("", "0")


HOLD_MS_ENV_VAR = "SWTPU_SANITIZE_HOLD_MS"
GRAPH_OUT_ENV_VAR = "SWTPU_SANITIZE_GRAPH_OUT"

_hold_warn_ms_cached: Optional[float] = None
_hold_env_checked = False


def hold_warn_ms() -> Optional[float]:
    """The configured hold-time warn threshold (ms), or None for
    today's default behavior (max-hold telemetry only, no warnings).
    A garbage value logs once and falls back to off, mirroring
    ``SWTPU_SANITIZE_EXPLORE``."""
    global _hold_warn_ms_cached, _hold_env_checked
    if _hold_env_checked:
        return _hold_warn_ms_cached
    raw = os.environ.get(HOLD_MS_ENV_VAR)
    if raw is None or raw == "":
        _hold_warn_ms_cached = None
    else:
        try:
            value = float(raw)
            if value <= 0:
                raise ValueError(raw)
            _hold_warn_ms_cached = value
        except ValueError:
            import logging
            logging.getLogger("shockwave_tpu.analysis").warning(
                "%s=%r is not a positive number of milliseconds; "
                "hold-time warnings stay off", HOLD_MS_ENV_VAR, raw)
            _hold_warn_ms_cached = None
    _hold_env_checked = True
    return _hold_warn_ms_cached


@dataclass
class Violation:
    kind: str      # "lock-order-cycle" | "unowned-access"
    message: str

    def __str__(self) -> str:
        return f"[{self.kind}] {self.message}"


class _Monitor:
    """Process-wide registry: order graph, hold times, violations.

    Lock names (not instances) are the graph nodes, so two scheduler
    incarnations in one test (crash/restart) share one ordering
    discipline — which is exactly the invariant we want checked.
    """

    #: Cap on retained hold-time warnings (the count keeps climbing).
    MAX_HOLD_WARNINGS = 200

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._cycle_reported: Set[tuple] = set()
        self._violations: List[Violation] = []
        self._max_hold: Dict[str, float] = {}
        #: Cumulative order graph: NOT cleared by reset(), so one
        #: process accumulates the union of every run's observed edges
        #: (the 20-seed explorer smoke resets per seed; the exported
        #: graph must cover all of them for the runtime ⊆ static gate).
        self._graph: Dict[str, Set[str]] = {}
        #: Holds exceeding the SWTPU_SANITIZE_HOLD_MS threshold
        #: (advisory telemetry, not violations — cleared by reset()).
        self._hold_warnings: List[dict] = []
        self._hold_warning_count = 0
        self._tls = threading.local()

    # -- per-thread held-lock stack ------------------------------------

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- events from SanitizedLock -------------------------------------

    def note_waiting(self, name: str) -> None:
        """Called BEFORE the (possibly blocking) inner acquire: the
        order edge and the cycle check must land while the thread can
        still report them — in an actual deadlock the acquire never
        returns, and a post-acquire record would name nothing."""
        held = self._held()
        if not held:
            return
        with self._mu:
            for outer in held:
                if outer == name:
                    continue
                self._edges.setdefault(outer, set()).add(name)
                self._graph.setdefault(outer, set()).add(name)
                if self._reaches(name, outer):
                    key = tuple(sorted((outer, name)))
                    if key not in self._cycle_reported:
                        self._cycle_reported.add(key)
                        self._violations.append(Violation(
                            "lock-order-cycle",
                            f"acquiring {name!r} while holding "
                            f"{outer!r}, but {outer!r} is also "
                            f"acquired while {name!r} is held "
                            "(deadlock potential)"))

    def note_acquired(self, name: str) -> None:
        self._held().append(name)

    def note_released(self, name: str, held_s: float) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break
        warn_ms = hold_warn_ms()
        with self._mu:
            if held_s > self._max_hold.get(name, 0.0):
                self._max_hold[name] = held_s
            if warn_ms is not None and held_s * 1000.0 >= warn_ms:
                self._hold_warning_count += 1
                if len(self._hold_warnings) < self.MAX_HOLD_WARNINGS:
                    self._hold_warnings.append(
                        {"lock": name,
                         "held_ms": round(held_s * 1000.0, 3)})

    def _reaches(self, src: str, dst: str) -> bool:
        """Whether dst is reachable from src in the order graph.
        Caller holds self._mu."""
        seen, frontier = set(), [src]
        while frontier:
            node = frontier.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            frontier.extend(self._edges.get(node, ()))
        return False

    # -- events from @requires_lock ------------------------------------

    def record_unowned(self, what: str) -> None:
        with self._mu:
            self._violations.append(Violation(
                "unowned-access",
                f"{what} entered without holding the receiver's lock"))

    # -- reporting -----------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                "violations": list(self._violations),
                "max_hold_s": dict(self._max_hold),
                "order_edges": {k: sorted(v)
                                for k, v in self._edges.items()},
                "hold_warn_ms": hold_warn_ms(),
                "hold_warnings": list(self._hold_warnings),
                "hold_warning_count": self._hold_warning_count,
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._cycle_reported.clear()
            self._violations.clear()
            self._max_hold.clear()
            self._hold_warnings.clear()
            self._hold_warning_count = 0
        # Per-thread held stacks are left alone on purpose: a daemon
        # thread mid-critical-section at reset time must still balance
        # its own acquires/releases. The cumulative `_graph` also
        # survives on purpose — it is the union the graph export
        # writes (see GRAPH_OUT_ENV_VAR).

    def cumulative_graph(self) -> dict:
        """The union of every observed (held -> acquired) edge since
        process start, in the static graph's export shape (see
        analysis/lockflow.py static_lock_order_graph)."""
        with self._mu:
            nodes: Set[str] = set()
            edges: List[str] = []
            for outer, inners in self._graph.items():
                nodes.add(outer)
                for inner in inners:
                    nodes.add(inner)
                    edges.append(f"{outer}->{inner}")
            return {"nodes": sorted(nodes), "edges": sorted(edges)}

    def export_graph(self, path: str) -> None:
        """Write the cumulative order graph as JSON (the runtime half
        of the runtime ⊆ static containment gate)."""
        import json
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.cumulative_graph(), f, indent=1,
                      sort_keys=True)
            f.write("\n")


_monitor = _Monitor()


def monitor() -> _Monitor:
    return _monitor


def _install_graph_export() -> None:
    """When SWTPU_SANITIZE_GRAPH_OUT names a path, dump the cumulative
    observed order graph there at interpreter exit. CI's containment
    gate feeds that file to ``python -m shockwave_tpu.analysis
    --assert-contains`` to check runtime edges ⊆ static edges."""
    path = os.environ.get(GRAPH_OUT_ENV_VAR)
    if not path:
        return
    import atexit
    atexit.register(_monitor.export_graph, path)


_install_graph_export()


class SanitizedLock:
    """Instrumented wrapper around an RLock (or Lock).

    Recursion-aware: order edges and hold timing fire on the outermost
    acquire/release only, so ``with self._cv:`` nested inside
    ``with self._lock:`` (same underlying lock) records one hold."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name
        self._local = threading.local()

    # -- depth bookkeeping (per thread) --------------------------------

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def _on_outermost_acquire(self) -> None:
        _monitor.note_acquired(self.name)
        self._local.t0 = time.monotonic()

    def _on_outermost_release(self) -> None:
        t0 = getattr(self._local, "t0", None)
        held_s = 0.0 if t0 is None else time.monotonic() - t0
        _monitor.note_released(self.name, held_s)

    # -- lock protocol -------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        outermost = self._depth() == 0
        if outermost:
            # Edge + cycle check BEFORE the potentially blocking inner
            # acquire (see note_waiting) — an attempted-but-failed
            # trylock still records the ordering fact, which is what
            # the discipline is about.
            _monitor.note_waiting(self.name)
            # Seeded interleaving exploration: perturb WHICH thread
            # wins the inner acquire (no-op unless installed).
            explorer.on_lock_event("acquire", self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            if outermost:
                self._on_outermost_acquire()
            self._local.depth = self._depth() + 1
        return got

    def release(self) -> None:
        depth = self._depth()
        self._inner.release()  # raises on unowned release before bookkeeping
        self._local.depth = max(depth - 1, 0)
        if depth <= 1:
            self._on_outermost_release()
            # Post-release perturbation: vary who enters next.
            explorer.on_lock_event("release", self.name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- private hooks Condition() relies on ---------------------------

    def _is_owned(self) -> bool:
        if self._depth() > 0:
            return True
        probe = getattr(self._inner, "_is_owned", None)
        return bool(probe()) if probe is not None else False

    def _release_save(self):
        depth = self._depth()
        self._local.depth = 0
        self._on_outermost_release()
        return (self._inner._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        _monitor.note_waiting(self.name)
        explorer.on_lock_event("acquire", self.name)
        self._inner._acquire_restore(inner_state)
        self._on_outermost_acquire()
        self._local.depth = depth

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} wrapping {self._inner!r}>"


def maybe_wrap(lock, name: str):
    """Instrument `lock` when the sanitizer is enabled; otherwise return
    it untouched (the production path — zero overhead)."""
    return SanitizedLock(lock, name) if enabled() else lock
