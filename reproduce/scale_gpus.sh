#!/bin/bash
# Scale microbenchmark: generated workloads on 64/128/256-chip simulated
# clusters (reference: reproduce/scale_{64,128,256}gpus.sh; paper Fig 9).
# Usage: reproduce/scale_gpus.sh <num_chips> [output_dir]
set -u
cd "$(dirname "$0")/.."
CHIPS=${1:?usage: scale_gpus.sh <num_chips> [output_dir]}
OUT=${2:-reproduce/pickles/scale_${CHIPS}}
JOBS=$((CHIPS * 120 / 32))   # keep load proportional to the canonical run
mkdir -p "$OUT"

for POLICY in shockwave max_min_fairness finish_time_fairness
do
    echo "=== ${CHIPS} chips / $POLICY ==="
    python3 scripts/drivers/simulate_generated.py \
        --num_jobs "$JOBS" \
        --policy "$POLICY" \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec "v100:${CHIPS}" \
        --round_duration 120 \
        --seed 0 \
        --config configs/tacc_32gpus.json \
        --output "$OUT/${POLICY}.pkl" \
        | tee "$OUT/${POLICY}.json"
done
