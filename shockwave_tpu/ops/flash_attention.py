"""Fused flash attention as a Pallas TPU kernel.

Forward pass is a blocked online-softmax kernel: the grid walks
(batch*heads, q-block, k-block) with the k-block dimension innermost, so
the f32 accumulator and running max/normalizer live in VMEM scratch
across k-steps and the full (T x T) score matrix never materializes in
HBM. Scores hit the MXU via `jnp.dot(..., preferred_element_type=f32)`.

Backward recomputes the (m, l) softmax statistics and the attention
probabilities blockwise with `lax.scan` in plain JAX — per-step
transients are O(BH * Tq * block_k), never the full score matrix —
using the standard flash-attention gradient formulas (Dao et al. '22).

The single-chip complement to parallel/ring_attention.py (which shards
the sequence across chips); the reference has no attention kernel at all
(vanilla torch softmax attention, workloads/pytorch/translation/
transformer/SubLayers.py).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128
SUBLANES = 8  # f32 tile height: mask/bias operands pad to this


def _fa_kernel(q_ref, k_ref, v_ref, kbias_ref, o_ref, m_scr, l_scr,
               acc_scr, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # With causal masking, k-blocks strictly above the diagonal contribute
    # nothing; skip their FLOPs entirely.
    should_run = True
    if causal:
        should_run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        # Key-padding bias: kbias_ref is a (1, SUBLANES, block_k) tile of
        # 0.0 (attend) / NEG_INF (masked), replicated across sublanes so
        # the block meets Mosaic's (8, 128) tiling; reduce one row out.
        s = s + jnp.max(kbias_ref[0], axis=0, keepdims=True)

        m_prev = m_scr[:, :1]                       # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                      # (block_q, block_k)
        correction = jnp.exp(m_prev - m_new)        # (block_q, 1)
        l_new = l_scr[:, :1] * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]  # (block_q, 1)
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _pad_axis(x, axis: int, to: int):
    pad = (-x.shape[axis]) % to
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _forward_impl(q, k, v, kv_mask, scale, causal, block_q, block_k,
                  interpret):
    """q: (BH, Tq, D); k,v: (BH, Tk, D); kv_mask: (BH, Tk) int8."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    grid = (bh, nq, nk)

    # Mosaic requires operand blocks whose last two dims tile to (8, 128),
    # so the (BH, Tk) key mask travels as a (BH, SUBLANES, Tk) f32 additive
    # bias (0 = attend, NEG_INF = masked), replicated across sublanes.
    kbias = jnp.where(kv_mask > 0, 0.0, NEG_INF).astype(jnp.float32)
    kbias = jnp.broadcast_to(kbias[:, None, :], (bh, SUBLANES, tk))

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_k), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v, kbias)
    return out


def _blockwise_stats(q, k, kv_mask, scale, causal, block_k):
    """Recompute per-row (m, l) softmax statistics with the same blocked
    online-softmax recurrence as the forward kernel, so the transient is
    O(BH * Tq * block_k), never the full score matrix."""
    tq = q.shape[1]
    tk = k.shape[1]
    nk = tk // block_k

    def per_bh(qb, kb, maskb):
        kb_blocks = kb.reshape(nk, block_k, -1)
        mask_blocks = maskb.reshape(nk, block_k)

        def body(carry, blk):
            m, l = carry
            kj, maskj, j = blk
            # Matmul in the storage dtype (bf16 on the MXU's native path)
            # with f32 accumulation — an f32 x f32 matmul would run at a
            # fraction of the bf16 MXU rate.
            s = lax.dot_general(
                qb, kj, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if causal:
                q_pos = lax.broadcasted_iota(jnp.int32, (tq, block_k), 0)
                k_pos = j * block_k + lax.broadcasted_iota(
                    jnp.int32, (tq, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            s = jnp.where(maskj[None, :] > 0, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            l = l * jnp.exp(m - m_new) + jnp.sum(
                jnp.exp(s - m_new[:, None]), axis=1)
            return (m_new, l), None

        (m, l), _ = lax.scan(
            body,
            (jnp.full((tq,), NEG_INF, jnp.float32),
             jnp.zeros((tq,), jnp.float32)),
            (kb_blocks, mask_blocks, jnp.arange(nk)))
        return m, l

    return jax.vmap(per_bh)(q, k, kv_mask)


def _backward_impl(q, k, v, kv_mask, out, g, scale, causal, block_k):
    """Flash-attention gradients by blockwise recompute (Dao et al.)."""
    bh, t, d = q.shape
    tk = k.shape[1]
    if causal:
        assert q.shape[1] == k.shape[1], "causal requires Tq == Tk"
    m, l = _blockwise_stats(q, k, kv_mask, scale, causal, block_k)
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)

    nk = tk // block_k
    g16 = g.astype(q.dtype)  # matmul operand dtype; accumulation is f32

    def mm(a, b, contract):
        # All backward matmuls run with storage-dtype (bf16) operands and
        # f32 accumulation (the Dao et al. recipe): an f32 x f32 matmul
        # would fall off the MXU's native bf16 path and dominate the
        # training step (measured 12.9% -> see EXPERIMENTS.md for the
        # compute-bound MFU this change recovers).
        return lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=jnp.float32)

    def per_bh(qb, kb, vb, gb, mb, lb, db, maskb):
        kb_blocks = kb.reshape(nk, block_k, d)
        vb_blocks = vb.reshape(nk, block_k, d)
        mask_blocks = maskb.reshape(nk, block_k)

        def body(dq, blk):
            kj, vj, maskj, j = blk
            s = mm(qb, kj, ((1,), (1,))) * scale         # (T, block_k) f32
            if causal:
                q_pos = lax.broadcasted_iota(jnp.int32, (t, block_k), 0)
                k_pos = j * block_k + lax.broadcasted_iota(
                    jnp.int32, (t, block_k), 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            s = jnp.where(maskj[None, :] > 0, s, NEG_INF)
            p = jnp.exp(s - mb[:, None]) / jnp.maximum(lb, 1e-30)[:, None]
            dp = mm(gb, vj, ((1,), (1,)))                # (T, block_k) f32
            ds = (p * (dp - db[:, None]) * scale).astype(qb.dtype)
            p16 = p.astype(qb.dtype)
            dq = dq + mm(ds, kj, ((1,), (0,)))
            dkj = mm(ds, qb, ((0,), (0,)))               # (block_k, d) f32
            dvj = mm(p16, gb, ((0,), (0,)))              # (block_k, d) f32
            return dq, (dkj, dvj)

        dq, (dk_blocks, dv_blocks) = lax.scan(
            body, jnp.zeros((t, d), jnp.float32),
            (kb_blocks, vb_blocks, mask_blocks, jnp.arange(nk)))
        return dq, dk_blocks.reshape(tk, d), dv_blocks.reshape(tk, d)

    dq, dk, dv = jax.vmap(per_bh)(q, k, v, g16, m, l, delta, kv_mask)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bhtd(q, k, v, kv_mask, scale, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _forward_impl(q, k, v, kv_mask, scale, causal, block_q, block_k,
                         interpret)


def _flash_bhtd_fwd(q, k, v, kv_mask, scale, causal, block_q, block_k):
    out = _flash_bhtd(q, k, v, kv_mask, scale, causal, block_q, block_k)
    return out, (q, k, v, kv_mask, out)


def _flash_bhtd_bwd(scale, causal, block_q, block_k, residuals, g):
    q, k, v, kv_mask, out = residuals
    dq, dk, dv = _backward_impl(q, k, v, kv_mask, out, g, scale, causal,
                                block_k)
    return dq, dk, dv, None


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    key_padding_mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024):
    """Fused attention for (batch, seq, heads, head_dim) inputs.

    head_dim is zero-padded to a multiple of 8 sublanes when ragged; it
    is NOT padded to the 128-lane tile — a full-coverage lane dim is
    legal in Mosaic and skipping the pad saves bandwidth (measured ~5%
    at d=64). Default blocks are large (1024) because per-grid-step
    overhead dominates on real v5e hardware: at (4, 2048, 8, 64) causal
    bf16, blocks of 1024 run 5.7x faster than blocks of 128 and 3.6x
    faster than the einsum path (0.47 ms vs 1.68 ms). Sequence lengths
    must be divisible by the block size (shrunk to T for short
    sequences); mask ragged sequences upstream. key_padding_mask is
    (B, Tk) with True = attend. Cross-attention (Tq != Tk) is supported
    for causal=False. Runs the Pallas TPU kernel on TPU and the Pallas
    interpreter elsewhere (tests/CI on CPU).
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if causal and tq != tk:
        raise ValueError("causal flash attention requires Tq == Tk")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"flash_attention requires seq lens divisible by the block "
            f"size; got Tq={tq}, Tk={tk}, blocks=({block_q}, {block_k})")

    def to_bhtd(x):
        t = x.shape[1]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, -1)
        return _pad_axis(x, 2, SUBLANES)

    qf, kf, vf = to_bhtd(q), to_bhtd(k), to_bhtd(v)
    if key_padding_mask is None:
        kv_mask = jnp.ones((b, tk), jnp.int8)
    else:
        kv_mask = key_padding_mask.astype(jnp.int8)  # (B, Tk), 1 = attend
    kv_mask = jnp.repeat(kv_mask, h, axis=0)  # (B*H, Tk), head-major rows
    out = _flash_bhtd(qf, kf, vf, kv_mask, float(scale), causal,
                      block_q, block_k)
    out = out[:, :tq, :d].reshape(b, h, tq, d)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
