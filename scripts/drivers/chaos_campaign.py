#!/usr/bin/env python3
"""Chaos campaign: N seeded randomized multi-fault schedules, each
checked against hard invariants — the harness that stops robustness
validation being one hand-written fault at a time.

Two execution modes share one resumable artifact:

- **sim** schedules drive `Scheduler.simulate(fault_events=...)` with a
  seeded mix of kill/revive (dead workers) and degrade/restore (gray
  failures: chips stay registered but run at a fraction of oracle
  speed) events over a subsample of the base trace.
- **physical** schedules drive the REAL control plane: a
  `run_physical.py` scheduler subprocess (journal + `SWTPU_SANITIZE=1`
  lock sanitizer on) against stub worker daemons
  (tests/fault_stub_worker.py), with a seeded `SWTPU_FAULTS` rule set
  (degrade + drop/delay/blackhole) and, on some seeds, a SIGKILLed
  worker mid-run.

Invariants asserted after every schedule (any violation makes the
campaign exit nonzero and is recorded in the artifact):

- every job completes (``all_jobs_completed``),
- exact step accounting (``steps_accounted``): static jobs must land
  EXACTLY on their step budget — a shortfall means steps were lost to
  a fault (or the job was dropped at the failure cap), an overshoot
  means a completion was double-counted; physical mode re-derives the
  budgets from the durable journal in a fresh process, independent of
  the live run. Adaptive (accordion/GNS) sim jobs rescale their
  budgets mid-flight, so they are checked as covered (>=) rather than
  exact. A job completed short of budget is tolerated ONLY when the
  books prove the scheduler's DEADLINE_SLACK policy fired (accounted
  run time > 1.5x expected duration — intended behavior when injected
  faults starve a job, recorded as ``deadline_dropped``),
- zero failure charges (``zero_failure_charges``): injected faults are
  the infrastructure's fault, never the job's. In simulation this is
  a sharp DIFFERENTIAL check: each schedule also runs once with its
  fault events stripped, and the injected faults must add ZERO failed
  micro-task aggregates over that baseline (the
  `swtpu_microtasks_total{outcome="failed"}` counter survives job
  completion, unlike `acct.failures`, which resets on success and is
  deleted at removal — so a fault-induced charge is caught even after
  every job drains). In physical mode transient charges are by design
  (a dropped Done's watchdog kill charges the attempt and the next
  success resets it), so the durable books are checked for residual
  charges — and a job actually dropped at the failure cap surfaces as
  a ``steps_accounted`` violation (its budget is short),
- physical only: the journal passes ``fsck_journal`` (exit 0,
  ``journal_fsck_clean``), the run was lock-sanitizer clean
  (``sanitizer_clean`` — SWTPU_SANITIZE=1 aborts the process on a
  violation, so a zero exit IS the assertion), and no lease wedged the
  round pipeline (``no_stuck_leases``: the drive finished inside its
  deadline with the trace drained).

Crash safety / reproducibility, same contract as sweep_scenarios.py:
the artifact is atomically rewritten after every schedule
(core/durable_io.write_text_atomic), schedules are keyed by seed and a
rerun skips completed ones (meta mismatch refuses without --restart),
and identical seeds+knobs produce a byte-equal artifact — all wall
telemetry stays on stderr / --timing_out.

Examples:
    # the committed study (sim only)
    python scripts/drivers/chaos_campaign.py \
        --trace data/canonical_120job.trace --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json --cluster_spec v100:8 \
        --round_duration 120 --num_schedules 40 \
        --out reproduce/chaos/chaos_campaign_40.json

    # the CI smoke (sim + one physical-loopback schedule)
    python scripts/drivers/chaos_campaign.py ... \
        --num_schedules 6 --physical_schedules 1 --out /tmp/chaos.json
"""
import argparse
import json
import os
import signal
import socket
import subprocess
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import driver_common  # noqa: E402
from shockwave_tpu.core.durable_io import write_text_atomic  # noqa: E402
from shockwave_tpu.core.metrics import parse_cluster_spec  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
RUN_PHYSICAL = os.path.join(REPO, "scripts", "drivers", "run_physical.py")
FSCK = os.path.join(REPO, "scripts", "utils", "fsck_journal.py")
# The jax-free real-process stub daemon the fault-injection suite
# already drives; the campaign reuses it as its loopback worker.
STUB_WORKER = os.path.join(REPO, "tests", "fault_stub_worker.py")

ARTIFACT_SCHEMA = 1
SIM_INVARIANTS = ("all_jobs_completed", "steps_accounted",
                  "zero_failure_charges")
PHYS_INVARIANTS = SIM_INVARIANTS + ("journal_fsck_clean",
                                    "sanitizer_clean", "no_stuck_leases")
TWIN_INVARIANTS = ("twin_all_jobs_completed", "twin_steps_accounted",
                   "twin_zero_failure_charges", "live_untouched")
#: Control-plane HA schedules (leader SIGKILLed or SIGSTOPped
#: mid-round; the hot standby must promote and finish the trace):
#: - promoted_clean: the standby exited 0 under SWTPU_SANITIZE=1 with
#:   every job completed (its exit gates the sanitizer too),
#: - exactly_one_writer: the journal's epoch chain has one contiguous
#:   writer span per epoch (a frozen zombie's post-fencing appends are
#:   discarded by the supersede rule, never interleaved),
#: - failover_within_budget: promotion landed within one round budget
#:   of the lease expiring,
#: - old_leader_fenced: a SIGCONTed frozen leader stood down with the
#:   fenced exit code instead of double-dispatching (vacuous for kill
#:   schedules — a SIGKILLed leader cannot misbehave).
HA_INVARIANTS = ("all_jobs_completed", "steps_accounted",
                 "zero_failure_charges", "journal_fsck_clean",
                 "exactly_one_writer", "failover_within_budget",
                 "promoted_clean", "old_leader_fenced")


chip_layout = driver_common.chip_layout


# ----------------------------------------------------------------------
# Sim schedules
# ----------------------------------------------------------------------

def draw_sim_schedule(rng, jobs, arrivals, cluster_spec, knobs):
    """One seeded multi-fault sim schedule: subsampled trace + a mixed
    kill/degrade event queue. Draw order is the schedule contract."""
    keep = max(2, int(round(
        float(rng.uniform(*knobs["subsample"])) * len(jobs))))
    idx = sorted(int(i) for i in rng.choice(len(jobs), size=min(
        keep, len(jobs)), replace=False))
    jobs = [jobs[i] for i in idx]
    arrivals = [arrivals[i] for i in idx]
    order = sorted(range(len(jobs)), key=lambda i: arrivals[i])
    jobs = [jobs[i] for i in order]
    arrivals = [arrivals[i] for i in order]

    layout = chip_layout(cluster_spec)
    types = sorted(layout)
    events = []
    n_kill = int(rng.poisson(knobs["kill_rate"]))
    for _ in range(n_kill):
        wt = types[int(rng.randint(len(types)))]
        k = min(int(rng.randint(1, knobs["max_chips"] + 1)),
                max(len(layout[wt]) - 1, 1))  # never kill the whole type
        ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                replace=False))
        at = float(rng.uniform(0.0, knobs["window_s"]))
        events.append({"at": round(at, 3), "kill": ids})
        events.append({"at": round(at + knobs["down_s"], 3),
                       "revive": ids, "worker_type": wt})
    n_degrade = int(rng.poisson(knobs["degrade_rate"]))
    for _ in range(n_degrade):
        wt = types[int(rng.randint(len(types)))]
        k = min(int(rng.randint(1, knobs["max_chips"] + 1)),
                len(layout[wt]))
        ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                replace=False))
        factor = round(float(rng.uniform(0.05, 0.5)), 6)
        at = float(rng.uniform(0.0, knobs["window_s"]))
        events.append({"at": round(at, 3), "degrade": ids,
                       "factor": factor})
        events.append({"at": round(at + knobs["down_s"], 3),
                       "restore": ids})
    events.sort(key=lambda e: e["at"])
    plan = {"num_jobs": len(jobs), "kills": n_kill, "degrades": n_degrade}
    return jobs, arrivals, events, plan


def run_sim_schedule(seed, cfg):
    """One sim schedule end to end; returns the deterministic record."""
    rng = np.random.RandomState(seed)
    jobs, arrivals = parse_trace(cfg["trace"])
    cluster_spec = parse_cluster_spec(cfg["cluster_spec"])
    jobs, arrivals, events, plan = draw_sim_schedule(
        rng, jobs, arrivals, cluster_spec, cfg["knobs"])
    profiles = build_profiles(jobs, cfg["throughput_table"])
    shockwave_config, serving_config, whatif_config, _ = (
        driver_common.load_configs(cfg["config"], cfg["policy"],
                                   cluster_spec, cfg["round_duration"]))

    def build():
        return driver_common.build_scheduler(
            cfg["policy"], cfg["throughputs"], profiles,
            round_duration=cfg["round_duration"], seed=seed,
            shockwave_config=shockwave_config,
            serving_config=serving_config,
            whatif_config=whatif_config)

    violations = []
    try:
        # Baseline leg: the SAME schedule with its faults stripped.
        # Some traces produce failed micro-task aggregates with no
        # faults at all (policy behavior, e.g. a failure-capped job
        # family); the invariant below is the DIFFERENTIAL — injected
        # faults must add zero failures over this baseline.
        import pickle
        baseline = build()
        base_jobs, base_arrivals = pickle.loads(
            pickle.dumps((jobs, arrivals)))  # simulate mutates Jobs
        baseline.simulate(cluster_spec, base_arrivals, base_jobs,
                          fault_events=[])
        from shockwave_tpu.obs import names as obs_names
        baseline_failed = baseline._obs.registry.value(
            obs_names.MICROTASKS_TOTAL, outcome="failed")

        sched = build()
        makespan = sched.simulate(cluster_spec, arrivals, jobs,
                                  fault_events=events)
    except Exception as e:  # noqa: BLE001 - a crash is the worst
        # invariant violation of all; it must land in the artifact, not
        # sink the campaign.
        return {"seed": seed, "plan": plan,
                "violations": [f"simulate raised "
                               f"{type(e).__name__}: {e}"],
                "invariants": {k: False for k in SIM_INVARIANTS}}

    completed = sched.get_num_completed_jobs()
    inv = {}
    inv["all_jobs_completed"] = completed == len(jobs)
    if not inv["all_jobs_completed"]:
        violations.append(f"{completed}/{len(jobs)} jobs completed")
    from shockwave_tpu.sched.scheduler import DEADLINE_SLACK
    short, over, deadline_dropped = [], [], []
    for j in jobs:
        run = sched.acct.total_steps_run.get(j.job_id, 0)
        if run >= j.total_steps:
            # Static budgets are immutable, so any overshoot is a
            # double-counted completion; adaptive modes rescale both
            # sides mid-flight and are only checked as covered.
            if j.mode == "static" and run > j.total_steps:
                over.append(str(j.job_id))
            continue
        run_time = (sum(sched.acct.run_time_per_worker
                        .get(j.job_id, {}).values())
                    / max(j.scale_factor, 1))
        if run_time > int(j.duration * DEADLINE_SLACK):
            # The scheduler's deadline policy force-completed a
            # fault-starved job — intended behavior, and the books
            # prove it (accounted run time over the slack budget).
            deadline_dropped.append(str(j.job_id))
        else:
            short.append(str(j.job_id))
    inv["steps_accounted"] = not short and not over
    if short:
        violations.append(f"step budget not covered for jobs {short} "
                          "(and not deadline-dropped)")
    if over:
        violations.append(f"step budget OVERSHOT for static jobs {over} "
                          "(double-counted completion?)")
    # Differential: faults must add zero failed micro-task aggregates
    # over the fault-free baseline of the same schedule (the counter
    # survives job completion, unlike acct.failures).
    failed_microtasks = sched._obs.registry.value(
        obs_names.MICROTASKS_TOTAL, outcome="failed")
    inv["zero_failure_charges"] = failed_microtasks <= baseline_failed
    if failed_microtasks > baseline_failed:
        violations.append(
            f"injected faults added "
            f"{failed_microtasks - baseline_failed:.0f} failed "
            f"micro-task aggregate(s) over the fault-free baseline "
            f"({baseline_failed:.0f})")
    return {"seed": seed, "plan": plan, "invariants": inv,
            "violations": violations,
            "summary": {"makespan": round(makespan, 2),
                        "rounds": sched.rounds.num_completed_rounds,
                        "completed_jobs": completed,
                        "failed_microtasks_baseline":
                            round(baseline_failed, 1),
                        "failed_microtasks_with_faults":
                            round(failed_microtasks, 1),
                        "deadline_dropped": deadline_dropped}}


# ----------------------------------------------------------------------
# Digital-twin shadow schedules (whatif/fork.py)
# ----------------------------------------------------------------------

def run_twin_schedule(seed, cfg):
    """One twin shadow schedule: run a subsampled trace FAULT-FREE with
    the what-if plane capturing a mid-run fork, then re-target this
    campaign's seeded fault mix at the DIGITAL TWIN — the same
    invariants, validated continuously against a fork instead of the
    live scheduler. Also asserts the live run was untouched by the
    forking (the twin-isolation contract)."""
    import pickle

    from shockwave_tpu.obs import names as obs_names
    from shockwave_tpu.sched.scheduler import DEADLINE_SLACK
    from shockwave_tpu.whatif import fork as whatif_fork

    rng = np.random.RandomState(seed)
    jobs, arrivals = parse_trace(cfg["trace"])
    cluster_spec = parse_cluster_spec(cfg["cluster_spec"])
    jobs, arrivals, events, plan = draw_sim_schedule(
        rng, jobs, arrivals, cluster_spec, cfg["knobs"])
    capture_round = int(rng.randint(3, 12))
    plan["capture_round"] = capture_round
    profiles = build_profiles(jobs, cfg["throughput_table"])
    shockwave_config, serving_config, _, _ = (
        driver_common.load_configs(cfg["config"], cfg["policy"],
                                   cluster_spec, cfg["round_duration"]))
    sched = driver_common.build_scheduler(
        cfg["policy"], cfg["throughputs"], profiles,
        round_duration=cfg["round_duration"], seed=seed,
        shockwave_config=shockwave_config,
        serving_config=serving_config,
        whatif_config={"capture_at_round": capture_round})

    violations = []
    inv = {k: False for k in TWIN_INVARIANTS}
    try:
        sched.simulate(cluster_spec, arrivals, jobs, fault_events=[])
        if sched._whatif.captured is None:
            # The subsampled schedule drained before the capture round;
            # nothing to shadow-validate — record a vacuous pass.
            return {"seed": seed, "plan": plan,
                    "invariants": {k: True for k in TWIN_INVARIANTS},
                    "violations": [],
                    "summary": {"captured": False}}
        live_before = pickle.dumps(sched.snapshot_state())
        blob, queued, remaining = sched._whatif.captured

        # Fault-free baseline twin (fresh obs: its failed-microtask
        # counter reflects the rollout alone), then the chaos twin.
        # Each leg gets its OWN deep copy of the queued tail — the sim
        # mutates admitted Job objects (id assignment, adaptive
        # batch-size rescales), and a shared copy would leak the
        # baseline leg's trajectory into the chaos leg.
        base_twin = whatif_fork.thaw(sched, blob)
        whatif_fork.rollforward(base_twin,
                                queued=pickle.loads(pickle.dumps(queued)),
                                remaining_jobs=remaining)
        base_failed = base_twin.obs.registry.value(
            obs_names.MICROTASKS_TOTAL, outcome="failed")

        twin = whatif_fork.thaw(sched, blob)
        whatif_fork.rollforward(twin,
                                queued=pickle.loads(pickle.dumps(queued)),
                                remaining_jobs=remaining,
                                fault_events=events)

        completed = twin.get_num_completed_jobs()
        inv["twin_all_jobs_completed"] = completed == len(jobs)
        if not inv["twin_all_jobs_completed"]:
            violations.append(
                f"twin: {completed}/{len(jobs)} jobs completed")
        short, over = [], []
        for j in jobs:
            if j.mode != "static":
                # Adaptive (accordion/GNS) budgets rescale along the
                # TWIN's own trajectory; the live `j.total_steps` here
                # reflects the base run's diverged adaptation history,
                # so only completion (checked above) is comparable.
                continue
            run = twin.acct.total_steps_run.get(j.job_id, 0)
            if run >= j.total_steps:
                if run > j.total_steps:
                    over.append(str(j.job_id))
                continue
            run_time = (sum(twin.acct.run_time_per_worker
                            .get(j.job_id, {}).values())
                        / max(j.scale_factor, 1))
            if run_time <= int(j.duration * DEADLINE_SLACK):
                short.append(str(j.job_id))
        inv["twin_steps_accounted"] = not short and not over
        if short:
            violations.append(f"twin: step budget not covered for "
                              f"{short} (and not deadline-dropped)")
        if over:
            violations.append(f"twin: step budget OVERSHOT for {over}")
        twin_failed = twin.obs.registry.value(
            obs_names.MICROTASKS_TOTAL, outcome="failed")
        inv["twin_zero_failure_charges"] = twin_failed <= base_failed
        if twin_failed > base_failed:
            violations.append(
                f"twin: injected faults added "
                f"{twin_failed - base_failed:.0f} failure charge(s)")
        inv["live_untouched"] = (pickle.dumps(sched.snapshot_state())
                                 == live_before)
        if not inv["live_untouched"]:
            violations.append("twin rollouts mutated the live "
                              "scheduler's state (fork isolation broken)")
        return {"seed": seed, "plan": plan, "invariants": inv,
                "violations": violations,
                "summary": {"captured": True,
                            "twin_makespan":
                                round(twin.get_current_timestamp(), 2),
                            "twin_completed": completed}}
    except Exception as e:  # noqa: BLE001 - a crash is the worst
        # violation of all; it must land in the artifact.
        violations.append(f"twin schedule raised {type(e).__name__}: {e}")
        return {"seed": seed, "plan": plan, "invariants": inv,
                "violations": violations}


# ----------------------------------------------------------------------
# Physical-loopback schedules
# ----------------------------------------------------------------------

def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def draw_physical_schedule(rng):
    """Seeded SWTPU_FAULTS rule set + worker plan for one loopback
    drive. Every schedule carries a gray failure (degrade that later
    expires, so recovery is exercised); the RPC-level faults and the
    mid-run worker SIGKILL are drawn per seed."""
    rules = [{
        "method": "execute", "action": "degrade",
        "factor": round(float(rng.uniform(0.05, 0.3)), 4),
        "after": int(rng.randint(1, 3)),
        "times": int(rng.randint(2, 5)),
    }]
    if rng.uniform() < 0.5:
        rules.append({"method": "Done", "action": "drop",
                      "times": int(rng.randint(1, 3))})
    if rng.uniform() < 0.4:
        rules.append({"method": "UpdateLease", "action": "delay",
                      "delay_s": round(float(rng.uniform(0.1, 0.4)), 3),
                      "times": int(rng.randint(1, 4))})
    if rng.uniform() < 0.3:
        rules.append({"method": "Ping", "action": "blackhole",
                      "delay_s": 2.0, "times": 1})
    plan = {
        "rules": rules,
        "num_workers": 2,
        # SIGKILL one worker mid-run on some seeds (jobs must finish on
        # the survivor with exact accounting).
        "kill_worker": bool(rng.uniform() < 0.4),
        "kill_after_s": round(float(rng.uniform(3.0, 8.0)), 2),
    }
    return plan


def _write_loopback_trace(path, num_jobs=2, steps=300):
    line = ("ResNet-18 (batch size 32)\tpython3 main.py "
            "--batch_size 32\timage_classification/cifar10\t"
            "--num_steps\t0\t{steps}\t1\tstatic\t1\t-1.000000\t10000\t0")
    with open(path, "w") as f:  # harness input, not durable state
        for _ in range(num_jobs):
            f.write(line.format(steps=steps) + "\n")
    return num_jobs, steps


def run_physical_schedule(seed, cfg, workdir):
    """One real-control-plane schedule: scheduler subprocess + stub
    worker daemons under a seeded fault rule set. Deterministic record
    (plans + invariant booleans); wall telemetry to stderr."""
    import pickle
    import time as _time  # wall-clock is subprocess babysitting only,
    # never in the record (call sites carry their own ignores)

    rng = np.random.RandomState(cfg["seed_base"] + 10_000 + seed)
    plan = draw_physical_schedule(rng)
    os.makedirs(workdir, exist_ok=True)
    trace = os.path.join(workdir, "loopback.trace")
    num_jobs, steps = _write_loopback_trace(trace)
    state_dir = os.path.join(workdir, "state")
    out_pickle = os.path.join(workdir, "metrics.pkl")
    sched_port = free_port()

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["SWTPU_SANITIZE"] = "1"          # lock sanitizer: abort on violation
    env["SWTPU_RPC_JITTER_SEED"] = str(seed)

    sched_log = open(os.path.join(workdir, "sched.log"), "w")
    sched = subprocess.Popen(
        [sys.executable, RUN_PHYSICAL, "--trace", trace,
         "--policy", "max_min_fairness",
         "--throughputs", cfg["throughputs"],
         "--expected_num_workers", str(plan["num_workers"]),
         "--round_duration", "2", "--port", str(sched_port),
         "--state_dir", state_dir, "--snapshot_interval", "2",
         "--output", out_pickle,
         "--heartbeat_interval", "0.2", "--worker_timeout", "1.0",
         "--probe_failures", "2", "--kill_wait", "0.5",
         "--completion_buffer", "5", "--first_init_grace", "0",
         "--quarantine_backoff", "3", "--verbose"],
        stdout=sched_log, stderr=subprocess.STDOUT, env=env)

    workers = []
    wenv = dict(env)
    wenv["SWTPU_FAULTS"] = json.dumps(plan["rules"])
    # Port-bind wait: subprocess babysitting, never in the record.
    deadline = _time.time() + 30  # swtpu-check: ignore[determinism]
    while _time.time() < deadline:  # swtpu-check: ignore[determinism]
        with socket.socket() as s:
            s.settimeout(0.2)
            try:
                s.connect(("127.0.0.1", sched_port))
                break
            except OSError:
                _time.sleep(0.1)
    for w in range(plan["num_workers"]):
        wlog = open(os.path.join(workdir, f"worker{w}.log"), "w")
        workers.append((subprocess.Popen(
            [sys.executable, STUB_WORKER,
             "--sched_port", str(sched_port),
             "--worker_port", str(free_port()), "--num_chips", "1",
             "--state_file", os.path.join(workdir, f"w{w}.json")],
            stdout=wlog, stderr=subprocess.STDOUT, env=wenv), wlog))

    violations = []
    inv = {k: False for k in PHYS_INVARIANTS}
    try:
        if plan["kill_worker"]:
            try:
                sched.wait(timeout=plan["kill_after_s"])
            except subprocess.TimeoutExpired:
                victim = workers[-1][0]
                if victim.poll() is None:
                    os.kill(victim.pid, signal.SIGKILL)
        try:
            rc = sched.wait(timeout=cfg["physical_timeout_s"])
            inv["no_stuck_leases"] = True
        except subprocess.TimeoutExpired:
            violations.append(
                f"scheduler did not finish within "
                f"{cfg['physical_timeout_s']}s (stuck lease / wedged "
                "round pipeline?)")
            sched.kill()
            rc = sched.wait(timeout=10)
        inv["sanitizer_clean"] = rc == 0
        if rc != 0:
            violations.append(f"scheduler exited {rc} under "
                              "SWTPU_SANITIZE=1")

        if os.path.exists(out_pickle):
            with open(out_pickle, "rb") as f:
                metrics = pickle.load(f)
            inv["all_jobs_completed"] = bool(
                metrics.get("all_jobs_completed"))
        if not inv["all_jobs_completed"]:
            violations.append("not all jobs completed")

        # Exact step accounting, re-derived from the DURABLE record —
        # the journal is the ground truth that survives the process.
        fsck = subprocess.run(
            [sys.executable, FSCK, state_dir], env=env,
            capture_output=True, text=True, timeout=60)
        inv["journal_fsck_clean"] = fsck.returncode == 0
        if fsck.returncode != 0:
            violations.append(
                f"fsck_journal exit {fsck.returncode}: "
                f"{fsck.stdout.strip().splitlines()[-1:]}")
        check = subprocess.run(
            [sys.executable, "-c", (
                "import sys; sys.path.insert(0, sys.argv[1])\n"
                "from shockwave_tpu.sched import journal\n"
                "from shockwave_tpu.sched.scheduler import Scheduler\n"
                "from shockwave_tpu.solver import get_policy\n"
                "s = Scheduler(get_policy('max_min_fairness'),"
                " throughputs_file=sys.argv[3])\n"
                "s.restore_from_durable_state("
                "journal.load_state(sys.argv[2]))\n"
                "import json\n"
                "print(json.dumps({str(k.integer_job_id()): v for k, v"
                " in s.acct.total_steps_run.items()}))\n"
                "print(json.dumps({str(k.integer_job_id()): v for k, v"
                " in s.acct.failures.items()}))"),
             REPO, state_dir, cfg["throughputs"]],
            env=env, capture_output=True, text=True, timeout=120)
        if check.returncode == 0:
            lines = check.stdout.strip().splitlines()
            steps_by_job = json.loads(lines[-2])
            failures = json.loads(lines[-1])
            # Exact equality: the loopback jobs are static, so a
            # shortfall means lost progress (or a failure-cap drop)
            # and an overshoot means a double-counted report.
            wrong = {j: s for j, s in steps_by_job.items()
                     if s != steps}
            inv["steps_accounted"] = (len(steps_by_job) == num_jobs
                                      and not wrong)
            if wrong or len(steps_by_job) != num_jobs:
                violations.append(
                    f"journal step accounting {steps_by_job} != "
                    f"{num_jobs}x{steps} exactly")
            charged = {j: c for j, c in failures.items() if c > 0}
            inv["zero_failure_charges"] = not charged
            if charged:
                violations.append(
                    f"failure charges under injected faults: {charged}")
        else:
            violations.append("journal replay cross-check failed: "
                              + check.stderr.strip()[-200:])
    finally:
        for proc in [sched] + [w for w, _ in workers]:
            try:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError) as e:
                # A subprocess stuck in uninterruptible sleep must not
                # sink the campaign: the schedule's record (and its
                # violations) is the contract, not this cleanup.
                print(f"[physical {seed}] cleanup of pid {proc.pid} "
                      f"failed: {e}", file=sys.stderr)
        sched_log.close()
        for _, wlog in workers:
            wlog.close()

    return {"seed": seed, "plan": plan, "invariants": inv,
            "violations": violations}


# ----------------------------------------------------------------------
# Control-plane HA schedules (leader-kill / leader-freeze failover)
# ----------------------------------------------------------------------

HA_ROUND_DURATION_S = 2.0
HA_KNOBS = {"lease_interval_s": 0.15, "lease_ttl_s": 0.8,
            "standby_poll_interval_s": 0.1, "failover_budget_s": 20.0}


def draw_ha_schedule(rng):
    """One seeded failover schedule: SIGKILL (dead leader) or SIGSTOP
    (wedged-but-ALIVE leader — the fenced split-brain drill, where the
    zombie is later SIGCONTed and must stand down) at a seeded point
    after real progress is journaled."""
    return {
        "mode": "freeze" if rng.uniform() < 0.5 else "kill",
        # Extra runway past the first journaled micro-task before the
        # fault lands, so schedules fail at varied round phases.
        "extra_runway_s": round(float(rng.uniform(0.0, 2.5)), 2),
        # Freeze only: how long after promotion the zombie stays
        # frozen before SIGCONT wakes it into its fencing.
        "thaw_after_promote_s": round(float(rng.uniform(0.3, 1.5)), 2),
        "num_workers": 2,
    }


def _journal_progress(state_dir):
    """(microtask_done count, job_removed count) from the live journal;
    (0, 0) while it is still unreadable/absent."""
    from shockwave_tpu.sched import journal as journal_mod
    try:
        rec = journal_mod.load_state(state_dir)
    except (journal_mod.JournalError, OSError):
        return 0, 0
    types = [e.get("type") for e in rec.events]
    if rec.snapshot is not None:
        # Compaction may have folded early micro-tasks into the
        # snapshot; the snapshot itself proves progress.
        return max(1, types.count("microtask_done")), types.count(
            "job_removed")
    return types.count("microtask_done"), types.count("job_removed")


def run_ha_schedule(seed, cfg, workdir):
    """One leader-kill/leader-freeze failover drive: HA leader +
    hot-standby run_physical subprocesses and stub workers, the leader
    faulted mid-round, every invariant re-derived from the durable
    journal afterwards. Deterministic record (plan + invariant booleans
    + exact journal accounting); wall telemetry stays on stderr."""
    import pickle
    import time as _time  # wall-clock is subprocess babysitting only,
    # never in the record (call sites carry their own ignores)

    sys.path.insert(0, os.path.join(REPO, "scripts", "utils"))
    import fsck_journal as fsck_mod  # noqa: E402

    rng = np.random.RandomState(cfg["seed_base"] + 30_000 + seed)
    plan = draw_ha_schedule(rng)
    os.makedirs(workdir, exist_ok=True)
    trace = os.path.join(workdir, "loopback.trace")
    num_jobs, steps = _write_loopback_trace(trace)
    state_dir = os.path.join(workdir, "state")
    out_standby = os.path.join(workdir, "standby_metrics.pkl")
    p_leader, p_standby = free_port(), free_port()

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["SWTPU_SANITIZE"] = "1"
    env["SWTPU_RPC_JITTER_SEED"] = str(seed)
    env["SWTPU_HA_ENDPOINT_FILE"] = os.path.join(state_dir,
                                                 "leader.lease")
    # The dead-leader window must fail fast so reports re-resolve
    # inside the failover budget instead of burning 90s retry budgets.
    env["SWTPU_RPC_DEADLINE_S"] = "5"
    env["SWTPU_RPC_BUDGET_S"] = "8"

    def sched_cmd(port, out, standby=False):
        cmd = [sys.executable, RUN_PHYSICAL, "--trace", trace,
               "--policy", "max_min_fairness",
               "--throughputs", cfg["throughputs"],
               "--expected_num_workers", str(plan["num_workers"]),
               "--round_duration", str(HA_ROUND_DURATION_S),
               "--port", str(port), "--state_dir", state_dir,
               "--snapshot_interval", "2", "--output", out,
               "--ha", json.dumps(HA_KNOBS),
               "--heartbeat_interval", "0.2", "--worker_timeout", "1.0",
               "--probe_failures", "2", "--kill_wait", "0.5",
               "--completion_buffer", "5", "--first_init_grace", "0",
               "--quarantine_backoff", "3", "--verbose"]
        if standby:
            cmd.append("--ha_standby")
        return cmd

    leader_log = open(os.path.join(workdir, "leader.log"), "w")
    leader = subprocess.Popen(
        sched_cmd(p_leader, os.path.join(workdir, "leader_metrics.pkl")),
        stdout=leader_log, stderr=subprocess.STDOUT, env=env)
    standby_log = open(os.path.join(workdir, "standby.log"), "w")
    standby = subprocess.Popen(
        sched_cmd(p_standby, out_standby, standby=True),
        stdout=standby_log, stderr=subprocess.STDOUT, env=env)

    deadline = _time.time() + 30  # swtpu-check: ignore[determinism]
    while _time.time() < deadline:  # swtpu-check: ignore[determinism]
        with socket.socket() as s:
            s.settimeout(0.2)
            try:
                s.connect(("127.0.0.1", p_leader))
                break
            except OSError:
                _time.sleep(0.1)
    workers = []
    for w in range(plan["num_workers"]):
        wlog = open(os.path.join(workdir, f"worker{w}.log"), "w")
        workers.append((subprocess.Popen(
            [sys.executable, STUB_WORKER,
             "--sched_port", str(p_leader),
             "--worker_port", str(free_port()), "--num_chips", "1",
             "--state_file", os.path.join(workdir, f"w{w}.json")],
            stdout=wlog, stderr=subprocess.STDOUT, env=wenv_ha(env)),
            wlog))

    violations = []
    inv = {k: False for k in HA_INVARIANTS}
    promo = None
    try:
        # Fault the leader only after real progress is journaled (and
        # before the trace drains), at a seeded extra offset.
        progress_deadline = _time.time() + 60  # swtpu-check: ignore[determinism]
        while _time.time() < progress_deadline:  # swtpu-check: ignore[determinism]
            if leader.poll() is not None:
                violations.append(
                    f"leader exited prematurely (rc {leader.returncode})")
                return {"seed": seed, "plan": plan, "invariants": inv,
                        "violations": violations}
            done, removed = _journal_progress(state_dir)
            if done >= 1 and removed < num_jobs:
                break
            _time.sleep(0.05)
        else:
            violations.append("no journaled progress within 60s")
            return {"seed": seed, "plan": plan, "invariants": inv,
                    "violations": violations}
        try:
            leader.wait(timeout=plan["extra_runway_s"])
            violations.append("leader finished before the fault landed")
            return {"seed": seed, "plan": plan, "invariants": inv,
                    "violations": violations}
        except subprocess.TimeoutExpired:
            pass
        fault_signal = (signal.SIGSTOP if plan["mode"] == "freeze"
                        else signal.SIGKILL)
        os.kill(leader.pid, fault_signal)
        if plan["mode"] == "kill":
            leader.wait(timeout=10)

        # The standby must promote unattended...
        promo_path = os.path.join(state_dir, "promotion.json")
        promo_deadline = _time.time() + 30  # swtpu-check: ignore[determinism]
        while _time.time() < promo_deadline:  # swtpu-check: ignore[determinism]
            if os.path.exists(promo_path):
                with open(promo_path) as f:
                    promo = json.load(f)
                break
            _time.sleep(0.1)
        if promo is None:
            violations.append("standby never promoted within 30s")

        if plan["mode"] == "freeze" and promo is not None:
            # ...and the thawed zombie must stand down FENCED (exit 7),
            # never double-dispatch.
            _time.sleep(plan["thaw_after_promote_s"])
            os.kill(leader.pid, signal.SIGCONT)
            try:
                rc_old = leader.wait(timeout=60)
                inv["old_leader_fenced"] = rc_old == 7
                if rc_old != 7:
                    violations.append(
                        f"SIGCONTed old leader exited {rc_old}, not the "
                        "fenced code 7")
            except subprocess.TimeoutExpired:
                violations.append("SIGCONTed old leader never exited "
                                  "(wedged past its fencing)")
                leader.kill()
        else:
            # A SIGKILLed leader cannot misbehave: vacuously fenced.
            inv["old_leader_fenced"] = plan["mode"] == "kill"

        try:
            rc = standby.wait(timeout=cfg["physical_timeout_s"])
        except subprocess.TimeoutExpired:
            violations.append("promoted standby did not finish within "
                              f"{cfg['physical_timeout_s']}s")
            standby.kill()
            rc = standby.wait(timeout=10)
        all_done = False
        if os.path.exists(out_standby):
            with open(out_standby, "rb") as f:
                all_done = bool(pickle.load(f).get("all_jobs_completed"))
        inv["promoted_clean"] = rc == 0 and all_done
        inv["all_jobs_completed"] = all_done
        if rc != 0:
            violations.append(f"promoted standby exited {rc} under "
                              "SWTPU_SANITIZE=1")
        if not all_done:
            violations.append("not all jobs completed after failover")
        if promo is not None:
            inv["failover_within_budget"] = (
                promo["from_lease_expiry_s"] <= HA_ROUND_DURATION_S)
            if not inv["failover_within_budget"]:
                violations.append(
                    f"promotion took {promo['from_lease_expiry_s']:.2f}s "
                    f"past lease expiry (> {HA_ROUND_DURATION_S}s round "
                    "budget)")

        # Durable-record invariants: exact accounting + fsck + the
        # exactly-one-writer epoch chain.
        accounting = {}
        fsck = subprocess.run(
            [sys.executable, FSCK, state_dir], env=env,
            capture_output=True, text=True, timeout=60)
        inv["journal_fsck_clean"] = fsck.returncode == 0
        if fsck.returncode != 0:
            violations.append(
                f"fsck_journal exit {fsck.returncode}: "
                f"{fsck.stdout.strip().splitlines()[-1:]}")
        from shockwave_tpu.sched import journal as journal_mod
        records = []
        for path in journal_mod.list_segments(state_dir):
            try:
                segment_records, _ = journal_mod.read_journal(path)
                records.extend(segment_records)
            except journal_mod.JournalError as e:
                violations.append(f"unreadable segment: {e}")
        notes = []
        epochs_ok, stale = fsck_mod.check_epoch_chain(records,
                                                      out=notes.append)
        inv["exactly_one_writer"] = epochs_ok
        if not epochs_ok:
            violations.extend(notes)
        check = subprocess.run(
            [sys.executable, "-c", (
                "import sys; sys.path.insert(0, sys.argv[1])\n"
                "from shockwave_tpu.sched import journal\n"
                "from shockwave_tpu.sched.scheduler import Scheduler\n"
                "from shockwave_tpu.solver import get_policy\n"
                "s = Scheduler(get_policy('max_min_fairness'),"
                " throughputs_file=sys.argv[3])\n"
                "s.restore_from_durable_state("
                "journal.load_state(sys.argv[2]))\n"
                "import json\n"
                "print(json.dumps({str(k.integer_job_id()): v for k, v"
                " in s.acct.total_steps_run.items()}))\n"
                "print(json.dumps({str(k.integer_job_id()): v for k, v"
                " in s.acct.failures.items()}))"),
             REPO, state_dir, cfg["throughputs"]],
            env=env, capture_output=True, text=True, timeout=120)
        if check.returncode == 0:
            lines = check.stdout.strip().splitlines()
            accounting = json.loads(lines[-2])
            failures = json.loads(lines[-1])
            wrong = {j: s for j, s in accounting.items() if s != steps}
            inv["steps_accounted"] = (len(accounting) == num_jobs
                                      and not wrong)
            if wrong or len(accounting) != num_jobs:
                violations.append(
                    f"journal step accounting {accounting} != "
                    f"{num_jobs}x{steps} exactly across the failover")
            charged = {j: c for j, c in failures.items() if c > 0}
            inv["zero_failure_charges"] = not charged
            if charged:
                violations.append(
                    f"failure charges across the failover: {charged}")
        else:
            violations.append("journal replay cross-check failed: "
                              + check.stderr.strip()[-200:])
        if promo is not None:
            print(f"[ha {seed}] {plan['mode']}: promotion "
                  f"{promo['from_lease_expiry_s']:.2f}s past lease "
                  f"expiry, applied_seq {promo['applied_seq']}, "
                  f"stale dropped {stale}", file=sys.stderr)
        # `stale` (how many zombie writes the supersede rule discarded)
        # is a RACE OUTCOME, not a schedule property — it stays on
        # stderr so the artifact remains byte-reproducible.
        return {"seed": seed, "plan": plan, "invariants": inv,
                "violations": violations,
                "summary": {"accounting": accounting,
                            "promoted_epoch": (promo or {}).get("epoch")}}
    finally:
        for proc in [leader, standby] + [w for w, _ in workers]:
            try:
                if proc.poll() is None:
                    # A still-frozen leader cannot act on SIGKILL.
                    try:
                        os.kill(proc.pid, signal.SIGCONT)
                    except OSError:
                        pass
                    proc.kill()
                    proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError) as e:
                print(f"[ha {seed}] cleanup of pid {proc.pid} "
                      f"failed: {e}", file=sys.stderr)
        leader_log.close()
        standby_log.close()
        for _, wlog in workers:
            wlog.close()


def wenv_ha(env):
    """Worker env for HA schedules: no injected RPC faults — the
    kill/freeze IS the fault (keeps the invariant booleans a pure
    function of the seed)."""
    wenv = dict(env)
    wenv.pop("SWTPU_FAULTS", None)
    return wenv


# ----------------------------------------------------------------------
# Artifact plumbing (sweep_scenarios.py contract)
# ----------------------------------------------------------------------

def write_artifact(path, meta, sim, physical, twin=None, ha=None):
    twin = twin or {}
    ha = ha or {}

    def _summary():
        records = (list(sim.values()) + list(physical.values())
                   + list(twin.values()) + list(ha.values()))
        bad = [r for r in records if r.get("violations")]
        return {
            "schedules": len(records),
            "passed": len(records) - len(bad),
            "violations": sorted(v for r in bad for v in r["violations"]),
        }
    doc = {"schema": ARTIFACT_SCHEMA, "meta": meta,
           "sim": {str(k): sim[k] for k in sorted(sim)},
           "physical": {str(k): physical[k] for k in sorted(physical)},
           "summary": _summary()}
    if twin:
        doc["twin"] = {str(k): twin[k] for k in sorted(twin)}
    if ha:
        doc["ha"] = {str(k): ha[k] for k in sorted(ha)}
    write_text_atomic(path, json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return doc


def main():
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--trace", required=True)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--cluster_spec", default="v100:8")
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--config", default=None,
                   help="scheduler config JSON (shockwave/serving blocks)")
    p.add_argument("--num_schedules", type=int, default=25,
                   help="seeded sim schedules")
    p.add_argument("--physical_schedules", type=int, default=0,
                   help="seeded physical-loopback schedules (real "
                        "scheduler + stub worker subprocesses; ~15-60s "
                        "each)")
    p.add_argument("--twin_schedules", type=int, default=0,
                   help="seeded digital-twin shadow schedules: the "
                        "fault mix runs against a what-if fork of a "
                        "mid-run scheduler (whatif/fork.py) instead of "
                        "the live one, checking the same invariants "
                        "plus fork isolation")
    p.add_argument("--ha_schedules", type=int, default=0,
                   help="seeded control-plane failover schedules: an HA "
                        "leader + hot-standby pair of real scheduler "
                        "subprocesses, the leader SIGKILLed or frozen "
                        "(SIGSTOP -> fenced SIGCONT) mid-round; gated "
                        "on exact accounting, exactly-one-writer-per-"
                        "epoch, and bounded failover (~20-40s each)")
    p.add_argument("--seed_base", type=int, default=0)
    p.add_argument("--out", required=True, help="results JSON artifact")
    p.add_argument("--restart", action="store_true",
                   help="ignore an existing artifact instead of resuming")
    p.add_argument("--workdir", default=None,
                   help="scratch dir for physical schedules (default: "
                        "<out>.work)")
    p.add_argument("--physical_timeout_s", type=float, default=120.0)
    # -- sim fault knobs --
    p.add_argument("--subsample", default="0.08:0.2", metavar="LO:HI")
    p.add_argument("--kill_rate", type=float, default=1.5)
    p.add_argument("--degrade_rate", type=float, default=1.5)
    p.add_argument("--max_chips", type=int, default=2)
    p.add_argument("--fault_window_s", type=float, default=15000.0)
    p.add_argument("--fault_down_s", type=float, default=4000.0)
    p.add_argument("--timing_out", default=None,
                   help="sidecar JSON with wall-clock timings")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()
    setup_logging("info" if args.verbose else "warning")

    try:
        lo, hi = (float(x) for x in args.subsample.split(":"))
    except ValueError:
        raise SystemExit(f"--subsample wants lo:hi, got "
                         f"{args.subsample!r}") from None
    knobs = {"subsample": (lo, hi), "kill_rate": args.kill_rate,
             "degrade_rate": args.degrade_rate,
             "max_chips": args.max_chips,
             "window_s": args.fault_window_s, "down_s": args.fault_down_s}
    meta = {
        "trace": args.trace, "policy": args.policy,
        "throughputs": args.throughputs,
        "cluster_spec": args.cluster_spec,
        "round_duration": args.round_duration, "config": args.config,
        "seed_base": args.seed_base,
        "knobs": {k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in knobs.items()},
    }

    sim, physical, twin, ha = {}, {}, {}, {}
    existing = driver_common.load_resumable_artifact(args.out, meta,
                                                     args.restart)
    if existing is not None:
        sim = {int(k): v for k, v in existing.get("sim", {}).items()}
        physical = {int(k): v
                    for k, v in existing.get("physical", {}).items()}
        twin = {int(k): v for k, v in existing.get("twin", {}).items()}
        ha = {int(k): v for k, v in existing.get("ha", {}).items()}

    from shockwave_tpu.core.oracle import read_throughputs
    cfg = {
        "trace": args.trace, "policy": args.policy,
        "throughputs": args.throughputs,
        "throughput_table": read_throughputs(args.throughputs),
        "cluster_spec": args.cluster_spec,
        "round_duration": args.round_duration, "config": args.config,
        "seed_base": args.seed_base, "knobs": knobs,
        "physical_timeout_s": args.physical_timeout_s,
    }

    import time as _time
    # Wall-clock is campaign-throughput telemetry only (stderr /
    # --timing_out); the artifact stays byte-deterministic.
    t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    workdir = args.workdir or (args.out + ".work")

    for i in range(args.num_schedules):
        if i in sim:
            continue
        record = run_sim_schedule(args.seed_base + i, cfg)
        sim[i] = record
        write_artifact(args.out, meta, sim, physical, twin, ha)
        status = "ok" if not record["violations"] else "VIOLATION"
        print(f"[sim {len(sim)}/{args.num_schedules}] seed "
              f"{args.seed_base + i} {status} "
              f"({_time.monotonic() - t0:.1f}s elapsed)",  # swtpu-check: ignore[determinism]
              file=sys.stderr, flush=True)

    for i in range(args.twin_schedules):
        if i in twin:
            continue
        # Disjoint seed space (physical uses +10_000).
        record = run_twin_schedule(args.seed_base + 20_000 + i, cfg)
        twin[i] = record
        write_artifact(args.out, meta, sim, physical, twin, ha)
        status = "ok" if not record["violations"] else "VIOLATION"
        print(f"[twin {len(twin)}/{args.twin_schedules}] seed "
              f"{args.seed_base + 20_000 + i} {status} "
              f"({_time.monotonic() - t0:.1f}s elapsed)",  # swtpu-check: ignore[determinism]
              file=sys.stderr, flush=True)

    for i in range(args.physical_schedules):
        if i in physical:
            continue
        record = run_physical_schedule(
            i, cfg, os.path.join(workdir, f"phys{i}"))
        physical[i] = record
        write_artifact(args.out, meta, sim, physical, twin, ha)
        status = "ok" if not record["violations"] else "VIOLATION"
        print(f"[physical {len(physical)}/{args.physical_schedules}] "
              f"seed {i} {status} "
              f"({_time.monotonic() - t0:.1f}s elapsed)",  # swtpu-check: ignore[determinism]
              file=sys.stderr, flush=True)

    for i in range(args.ha_schedules):
        if i in ha:
            continue
        record = run_ha_schedule(i, cfg, os.path.join(workdir, f"ha{i}"))
        ha[i] = record
        write_artifact(args.out, meta, sim, physical, twin, ha)
        status = "ok" if not record["violations"] else "VIOLATION"
        print(f"[ha {len(ha)}/{args.ha_schedules}] seed {i} "
              f"({record['plan']['mode']}) {status} "
              f"({_time.monotonic() - t0:.1f}s elapsed)",  # swtpu-check: ignore[determinism]
              file=sys.stderr, flush=True)

    doc = write_artifact(args.out, meta, sim, physical, twin, ha)
    summary = doc["summary"]
    wall_s = _time.monotonic() - t0  # swtpu-check: ignore[determinism]
    result = {"artifact": args.out, **summary,
              "wall_s": round(wall_s, 2)}
    print(json.dumps(result))
    if args.timing_out:
        # Telemetry sidecar, not durable state.
        with open(args.timing_out, "w") as f:
            json.dump(result, f, indent=2)
    if summary["violations"]:
        print(f"CHAOS CAMPAIGN FAILED: {len(summary['violations'])} "
              "invariant violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
