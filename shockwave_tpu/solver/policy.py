"""Policy base classes.

Every policy maps scheduler state to a time-fraction allocation:
`get_allocation(...) -> {job_id: {worker_type: fraction}}` where fractions
are the share of wall-clock time each job (combination) should spend on
each worker type (reference: scheduler/policies/policy.py).

The flatten/unflatten helpers convert between the nested-dict form the
scheduler uses and the dense matrices the LPs operate on. The packing base
additionally handles JobIdPair combination keys whose throughput entries
are per-member lists.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core.job import JobIdPair


class Policy:
    name = "Policy"

    def __init__(self, solver: Optional[str] = None):
        # `solver` kept for interface compatibility; HiGHS is always used.
        self._solver = solver
        self._num_workers: Optional[List[int]] = None

    def flatten(self, d: dict, cluster_spec: dict):
        """2-level dict -> (m x n) matrix plus (job_ids, worker_types) index."""
        job_ids = sorted(d.keys())
        if not job_ids:
            return None, None
        worker_types = sorted(d[job_ids[0]].keys())
        if not worker_types:
            return None, None
        self._num_workers = [cluster_spec[wt] for wt in worker_types]
        m = np.array([[d[job_id][wt] for wt in worker_types] for job_id in job_ids],
                     dtype=float)
        return m, (job_ids, worker_types)

    def unflatten(self, matrix, index) -> dict:
        job_ids, worker_types = index
        return {
            job_id: {wt: float(matrix[i][j]) for j, wt in enumerate(worker_types)}
            for i, job_id in enumerate(job_ids)
        }

    def scale_factors_array(self, scale_factors: dict, job_ids, m: int, n: int):
        arr = np.zeros((m, n))
        for i in range(m):
            arr[i, :] = scale_factors[job_ids[i]]
        return arr

    # -- LP constraint helpers (dense rows over an m*n flattened x) --------

    @staticmethod
    def cluster_capacity_rows(m: int, n: int, scale_factors_array, num_workers,
                              num_extra_vars: int = 0):
        """Rows for: sum_i sf_i * x[i, j] <= num_workers[j], for each j."""
        rows, rhs = [], []
        for j in range(n):
            row = np.zeros(m * n + num_extra_vars)
            for i in range(m):
                row[i * n + j] = scale_factors_array[i, j]
            rows.append(row)
            rhs.append(num_workers[j])
        return rows, rhs

    @staticmethod
    def job_time_rows(m: int, n: int, num_extra_vars: int = 0):
        """Rows for: sum_j x[i, j] <= 1, for each i."""
        rows, rhs = [], []
        for i in range(m):
            row = np.zeros(m * n + num_extra_vars)
            row[i * n:(i + 1) * n] = 1.0
            rows.append(row)
            rhs.append(1.0)
        return rows, rhs


class PolicyWithPacking(Policy):
    """Base for policies over job combinations (pairs sharing one device)."""

    name = "PolicyWithPacking"

    def flatten(self, d: dict, cluster_spec: dict, priority_weights: Optional[dict] = None):
        """Returns per-single-job throughput tensors.

        d maps JobIdPair (single or pair) -> worker_type -> throughput
        (scalar for singles, [tput_a, tput_b] for pairs). Result: tensor of
        shape (num_singles, num_combinations, num_worker_types) where entry
        [s, c, w] is single job s's throughput inside combination c.
        """
        job_ids = sorted(d.keys())
        if not job_ids:
            return None, None
        worker_types = sorted(d[job_ids[0]].keys())
        if not worker_types:
            return None, None
        self._num_workers = [cluster_spec[wt] for wt in worker_types]

        single_job_ids = [j for j in job_ids if not j.is_pair()]
        relevant: Dict[JobIdPair, List[int]] = {s: [] for s in single_job_ids}
        for idx, job_id in enumerate(job_ids):
            for s in job_id.singletons():
                if s in relevant:
                    relevant[s].append(idx)

        tensor = np.zeros((len(single_job_ids), len(job_ids), len(worker_types)),
                          dtype=np.float32)
        for si, s in enumerate(single_job_ids):
            for ci in relevant[s]:
                combo = job_ids[ci]
                for wi, wt in enumerate(worker_types):
                    if combo.is_pair():
                        member = combo.as_tuple().index(s[0])
                        tensor[si, ci, wi] = d[combo][wt][member]
                    elif combo == s:
                        tensor[si, ci, wi] = d[combo][wt]
            if priority_weights is not None:
                tensor[si] /= priority_weights[s]
        return tensor, (job_ids, single_job_ids, worker_types, relevant)

    def unflatten(self, matrix, index) -> dict:
        job_ids, _, worker_types, _ = index
        return {
            job_id: {wt: float(matrix[i][j]) for j, wt in enumerate(worker_types)}
            for i, job_id in enumerate(job_ids)
        }

    def scale_factors_array(self, scale_factors: dict, job_ids, m: int, n: int):
        arr = np.zeros((m, n))
        for i, job_id in enumerate(job_ids):
            sfs = {scale_factors[s] for s in job_id.singletons()}
            arr[i, :] = sfs.pop() if len(sfs) == 1 else 0
        return arr

    def normalized_effective_rows(self, tensor, index, sf,
                                  unflattened_throughputs, cluster_spec,
                                  proportional_policy):
        """E[si] . x = single job si's effective throughput normalized by
        its proportional share, plus the (combo, worker) vars to pin to 0
        because the combo's members have mismatched scale factors."""
        job_ids, single_job_ids, worker_types, relevant = index
        num_singles, m, n = tensor.shape
        iso = np.array([
            [unflattened_throughputs[s][wt] for wt in worker_types]
            for s in single_job_ids
        ])
        proportional = proportional_policy.get_throughputs(
            iso, (single_job_ids, worker_types), cluster_spec)
        E = np.zeros((num_singles, m * n))
        for si, s in enumerate(single_job_ids):
            for ci in relevant[s]:
                E[si, ci * n:(ci + 1) * n] = (
                    tensor[si, ci] * sf[ci] / proportional[si, 0])
        fixed = [i * n + j for i in range(m) for j in range(n)
                 if sf[i, j] == 0]
        return E, fixed

    @staticmethod
    def per_job_time_rows(job_ids, single_job_ids, relevant, n: int,
                          num_extra_vars: int = 0):
        """Rows for: total share of each single job across combos <= 1."""
        m = len(job_ids)
        rows, rhs = [], []
        for s in single_job_ids:
            row = np.zeros(m * n + num_extra_vars)
            for ci in relevant[s]:
                row[ci * n:(ci + 1) * n] = 1.0
            rows.append(row)
            rhs.append(1.0)
        return rows, rhs
