#!/bin/bash
exec "$(dirname "$0")/scale_gpus.sh" 64 "$@"
