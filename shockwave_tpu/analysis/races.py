"""Lockset race detector: whole-tree, no opt-in.

For every class whose methods are reachable from the discovered thread
roots (analysis/threads.py), every instance field is checked RacerD
style: collect each ``self.<field>`` access with (a) the set of locks
lexically held there (``with self._lock`` / ``with self._cv`` /
``@requires_lock``), and (b) the set of thread roots that statically
reach the enclosing method. A field accessed from two different roots
— or from one *self-concurrent* root, a gRPC/HTTP handler pool — whose
access-site locksets share no common lock, and written at least once
outside ``__init__``, is a race finding.

The verdict can be *documented* instead of lexically proven, through
two class-body registries:

- ``_LOCK_PROTECTED = frozenset({...})`` — the field is guarded by the
  instance's own ``self._lock``/``self._cv``; the lock-discipline pass
  enforces the lexical claim and the runtime sanitizer enforces
  ``@requires_lock`` ownership dynamically.
- ``_EXTERNALLY_SYNCHRONIZED = frozenset({...})`` — the field's
  synchronization lives outside the class: the owning scheduler's lock
  held at every call site, or single-thread confinement. The static
  detector cannot see a caller's lock, so the declaration (with its
  justifying comment) is the documented verdict; the runtime sanitizer
  and the interleaving explorer are the checks that keep it honest.

Registries are resolved hierarchy-wide (a field declared protected by
``PhysicalScheduler`` covers accesses in ``Scheduler`` methods — the
sim-mode instance is single-threaded, the physical subclass carries
the locking story for both).

Exemptions, each of which removes a whole class of false positives:

- fields that ARE synchronization (locks, conditions, ``Event``,
  ``queue.Queue``, ``threading.local`` — their own thread safety);
- fields never written outside ``__init__`` (immutable configuration
  and injected handles);
- accesses inside ``__init__`` itself (the object has not escaped its
  constructing thread);
- methods no thread root reaches (construction helpers, dead code).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .core import (Finding, RepoIndex, SourceFile, decorated_requires_lock,
                   finding, is_self_attr, literal_str_set)
from .threads import (CALLBACK_ROOT_KWARGS, RPC_SERVE_FUNCS,
                      SELF_CONCURRENT_KINDS, CallGraph, FuncKey,
                      discover_thread_roots)

PASS_ID = "race-detector"

#: Class-body registry documenting externally synchronized fields.
EXTERNAL_REGISTRY_NAME = "_EXTERNALLY_SYNCHRONIZED"
LOCK_REGISTRY_NAME = "_LOCK_PROTECTED"

#: Default lock attribute names honored even without a detected
#: constructor assignment (mirrors the lock-discipline pass).
DEFAULT_LOCK_ATTRS = frozenset({"_lock", "_cv"})

#: Container-method calls that mutate the receiver in place: a call of
#: one of these on a field counts as a WRITE to that field.
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "update", "clear",
    "discard", "remove", "extend", "insert", "setdefault", "popitem",
})

#: Sync-field kinds that make a field exempt (thread-safe by type).
#: Deliberately excludes deque: iterating one while another thread
#: appends raises RuntimeError — a deque ring still needs a lock.
SAFE_SYNC_KINDS = frozenset({"lock", "event", "queue", "tls"})


@dataclass
class Access:
    field: str
    write: bool
    locks: FrozenSet[str]
    src: SourceFile
    line: int
    func: FuncKey


def _class_registry(graph: CallGraph, cls: str) -> Set[str]:
    """Union of both registries over the class family (ancestors and
    descendants): a declaration anywhere in the hierarchy documents the
    field for every instance shape."""
    family = set(graph.mro(cls))
    for sub in graph.subclasses(cls):
        family.add(sub)
        family.update(graph.mro(sub))
    out: Set[str] = set()
    for name in family:
        info = graph.classes.get(name)
        if info is None:
            continue
        for stmt in info.node.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id in (LOCK_REGISTRY_NAME,
                                               EXTERNAL_REGISTRY_NAME)):
                declared = literal_str_set(stmt.value)
                if declared:
                    out |= declared
    return out


def _is_lock_attr(graph: CallGraph, cls: str, attr: str) -> bool:
    if attr in DEFAULT_LOCK_ATTRS:
        return True
    for name in graph.mro(cls):
        if graph.sync_fields.get((name, attr)) == "lock":
            return True
    return False


def _collect_accesses(graph: CallGraph, fi) -> List[Access]:
    """Field accesses of one method with lexical locksets; nested
    function definitions are skipped (they are their own nodes and
    their bodies run with their own — empty — lock context)."""
    cls = fi.cls
    out: List[Access] = []
    base_locks: FrozenSet[str] = frozenset()
    if decorated_requires_lock(fi.node):
        base_locks = frozenset({graph.canonical_lock(cls, "_lock")})

    def record(node: ast.Attribute, write: bool,
               locks: FrozenSet[str]) -> None:
        out.append(Access(node.attr, write, locks, fi.src, node.lineno,
                          fi.key))

    def scan(node: ast.AST, locks: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fi.node:
            return  # separate node; analyzed on its own
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(locks)
            for item in node.items:
                expr = item.context_expr
                if (is_self_attr(expr)
                        and _is_lock_attr(graph, cls, expr.attr)):
                    inner.add(graph.canonical_lock(cls, expr.attr))
            for child in ast.iter_child_nodes(node):
                scan(child, frozenset(inner))
            return
        if isinstance(node, ast.Lambda):
            scan(node.body, frozenset())
            return
        # Mutator-method call on a field: self.f.append(x).
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS
                    and is_self_attr(fn.value)):
                record(fn.value, True, locks)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    scan(arg, locks)
                return
        # Subscript store/delete through a field: self.f[k] = v.
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target] if isinstance(node, ast.AugAssign)
                       else node.targets if isinstance(node, ast.Delete)
                       else [])
            for target in targets:
                for sub in ast.walk(target):
                    if (isinstance(sub, ast.Subscript)
                            and is_self_attr(sub.value)):
                        record(sub.value, True, locks)
        if isinstance(node, ast.Attribute) and is_self_attr(node):
            record(node, isinstance(node.ctx, (ast.Store, ast.Del)), locks)
            return
        for child in ast.iter_child_nodes(node):
            scan(child, locks)

    for child in fi.node.body:
        scan(child, base_locks)
    return out


def check_race_detector(index: RepoIndex,
                        rpc_serve_funcs: Iterable[str] = RPC_SERVE_FUNCS,
                        callback_kwargs: Iterable[str]
                        = CALLBACK_ROOT_KWARGS) -> List[Finding]:
    """Whole-tree lockset race detection (see module docstring)."""
    graph = index.call_graph()
    roots, _ = discover_thread_roots(index, rpc_serve_funcs,
                                     callback_kwargs)
    if not roots:
        return []

    # -- thread-entry -> reachable-methods map ------------------------
    # Root identity is the ENTRY FUNCTION (+kind): two spawn sites of
    # the same loop are one logical thread body.
    root_reach: Dict[Tuple[str, str], Set[FuncKey]] = {}
    for root in roots:
        rid = (str(root.key), root.kind)
        if rid not in root_reach:
            root_reach[rid] = graph.reachable(root.key)

    func_roots: Dict[FuncKey, Set[Tuple[str, str]]] = {}
    for rid, reach in root_reach.items():
        for key in reach:
            func_roots.setdefault(key, set()).add(rid)

    # -- analyzed class families --------------------------------------
    touched_classes = {key.cls for key in func_roots if key.cls}
    families: Set[str] = set()
    for cls in touched_classes:
        for name in graph.mro(cls):
            families.add(name)
        for name in graph.subclasses(cls):
            families.add(name)
    if not families:
        return []

    # -- the implicit main root: public surface of analyzed classes ---
    # The driving thread (a script's main, a test) can call any public
    # method; __init__ is excluded (pre-escape construction).
    MAIN = ("<main>", "main")
    for cls in sorted(families):
        info = graph.classes[cls]
        for mname, fi in info.methods.items():
            if mname.startswith("_") or "." in mname:
                continue
            for key in graph.reachable(fi.key):
                func_roots.setdefault(key, set()).add(MAIN)

    # -- collect accesses per defining class --------------------------
    per_class: Dict[str, List[Access]] = {}
    for key, fi in graph.funcs.items():
        if fi.cls is None or fi.cls not in families:
            continue
        if key.name == "__init__" or key.name.startswith("__init__.<locals>"):
            continue
        if key not in func_roots:
            continue  # unreached: construction helper or dead code
        per_class.setdefault(fi.cls, []).extend(_collect_accesses(graph, fi))

    # -- merge up the hierarchy: accesses in base-class methods join
    #    the most-derived analyzed family member's field table ---------
    findings: List[Finding] = []
    fields: Dict[Tuple[str, str], List[Access]] = {}
    for cls in sorted(per_class):
        # Anchor each class's accesses at the ROOT of its family so
        # PhysicalScheduler + Scheduler share one table.
        mro = graph.mro(cls)
        anchor = mro[-1] if mro else cls
        for access in per_class[cls]:
            fields.setdefault((anchor, access.field), []).append(access)

    registry_memo: Dict[str, Set[str]] = {}
    for (anchor, field_name) in sorted(fields,
                                       key=lambda k: (k[0], k[1])):
        accesses = fields[(anchor, field_name)]
        cls = accesses[0].func.cls or anchor
        if anchor not in registry_memo:
            registry_memo[anchor] = _class_registry(graph, anchor)
        if field_name in registry_memo[anchor]:
            continue  # documented verdict (lock-discipline enforces
            # the _LOCK_PROTECTED half lexically)
        if _sync_kind(graph, cls, field_name) in SAFE_SYNC_KINDS:
            continue
        if _is_lock_attr(graph, cls, field_name):
            continue
        rooted = [a for a in accesses if func_roots.get(a.func)]
        if not rooted:
            continue
        writes = [a for a in rooted if a.write]
        if not writes:
            continue  # written only during construction: immutable
        distinct: Set[Tuple[str, str]] = set()
        for a in rooted:
            distinct |= func_roots[a.func]
        concurrent = (len(distinct) > 1
                      or any(kind in SELF_CONCURRENT_KINDS
                             for _, kind in distinct))
        if not concurrent:
            continue
        common = frozenset.intersection(*[a.locks for a in rooted])
        if common:
            continue  # a consistent lockset covers every access
        # Anchor the finding at the most actionable site: a lock-free
        # write if any, else a lock-free read, else the first write.
        bare_writes = [a for a in writes if not a.locks]
        bare_reads = [a for a in rooted if not a.locks]
        anchor_access = min(bare_writes or bare_reads or writes,
                            key=lambda a: (a.src.rel, a.line))
        root_names = sorted({entry for entry, _ in distinct})
        f = finding(
            anchor_access.src, anchor_access.line, PASS_ID,
            f"field 'self.{field_name}' of {cls} is reachable from "
            f"{len(distinct)} thread root(s) ({', '.join(root_names[:4])}"
            f"{', ...' if len(root_names) > 4 else ''}) with no common "
            "lock across its access sites: hold one lock at every "
            "access, or document the verdict in _LOCK_PROTECTED / "
            "_EXTERNALLY_SYNCHRONIZED")
        if f is not None:
            findings.append(f)
    return findings


def _sync_kind(graph: CallGraph, cls: str, attr: str) -> Optional[str]:
    for name in graph.mro(cls):
        kind = graph.sync_fields.get((name, attr))
        if kind is not None:
            return kind
    return None
