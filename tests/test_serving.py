"""Serving-tier tests: load/latency/autoscaler units, mixed-trace
co-scheduling simulations (spike preemption, scale-to-zero, FTF
envelope), KV-cache decode parity, journal replay of serving state, a
runtime-marked replica lease loopback, and the hardened TPU liveness
probe."""
import json
import os
import socket
import threading
import time

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.core.trace import (is_serving_job, job_to_trace_line,
                                      make_serving_job,
                                      parse_serving_command, parse_trace,
                                      serving_command,
                                      serving_service_rate)
from shockwave_tpu.sched.scheduler import Scheduler, SchedulerConfig
from shockwave_tpu.serving.autoscaler import Autoscaler, AutoscalerConfig
from shockwave_tpu.serving.latency_model import (SATURATED, erlang_c,
                                                 p50_latency, p99_latency,
                                                 replicas_for_slo)
from shockwave_tpu.serving.load import DiurnalLoad, Spike, seeded_spikes
from shockwave_tpu.solver import get_policy

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DATA = os.path.join(REPO, "data")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")


def train_job(steps=40000, duration=4000, sf=1):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=steps, duration=duration, scale_factor=sf)


# ----------------------------------------------------------------------
# Load model
# ----------------------------------------------------------------------

class TestDiurnalLoad:
    def test_day_curve_trough_and_peak(self):
        load = DiurnalLoad(base_rps=10, peak_rps=30, period_s=86400)
        assert load.rate(0) == pytest.approx(10)          # phase-0 trough
        assert load.rate(43200) == pytest.approx(30)      # half period
        assert load.rate(86400) == pytest.approx(10)

    def test_spike_multiplies_day_value(self):
        load = DiurnalLoad(10, 10, 0, spikes=[Spike(100, 50, 10.0)])
        assert load.rate(99) == pytest.approx(10)
        assert load.rate(100) == pytest.approx(100)
        assert load.rate(149.9) == pytest.approx(100)
        assert load.rate(150) == pytest.approx(10)

    def test_peak_rate_sees_mid_window_spike(self):
        """The autoscaler provisions for the window's peak, so a spike
        starting mid-round must be visible at the round's dispatch."""
        load = DiurnalLoad(10, 10, 0, spikes=[Spike(60, 600, 10.0)])
        assert load.peak_rate(0, 120) == pytest.approx(100)
        assert load.mean_rate(0, 120) < 100

    def test_seeded_spikes_deterministic_and_bounded(self):
        a = seeded_spikes(7, 10000, 3, 10.0, 600)
        b = seeded_spikes(7, 10000, 3, 10.0, 600)
        assert a == b
        assert len(a) == 3
        for spike in a:
            assert 0.05 * 10000 <= spike.start <= 0.85 * 10000
        assert seeded_spikes(8, 10000, 3, 10.0, 600) != a

    def test_rejects_inverted_bounds(self):
        with pytest.raises(ValueError):
            DiurnalLoad(base_rps=10, peak_rps=5, period_s=100)


# ----------------------------------------------------------------------
# Latency model
# ----------------------------------------------------------------------

class TestLatencyModel:
    def test_erlang_c_limits(self):
        assert erlang_c(4, 0.0) == 0.0
        assert erlang_c(4, 4.0) == 1.0       # at saturation
        assert erlang_c(0, 1.0) == 1.0
        assert 0.0 < erlang_c(4, 2.0) < 1.0

    def test_p99_monotone_in_replicas(self):
        lam, mu = 80.0, 25.0
        lat = [p99_latency(lam, c, mu) for c in range(4, 10)]
        assert lat[0] == SATURATED or lat[0] > lat[-1]
        assert all(a >= b for a, b in zip(lat, lat[1:]))
        assert p50_latency(lam, 8, mu) <= p99_latency(lam, 8, mu)

    def test_saturation_and_idle(self):
        assert p99_latency(100.0, 3, 25.0) == SATURATED   # lam > c*mu
        assert p99_latency(0.0, 3, 25.0) == pytest.approx(1 / 25.0)

    def test_replicas_for_slo(self):
        # 92 req/s at mu=25, slo 0.5 s: 4 replicas wait too long, 5 fit.
        assert p99_latency(92.0, 4, 25.0) > 0.5
        assert p99_latency(92.0, 5, 25.0) <= 0.5
        assert replicas_for_slo(92.0, 25.0, 0.5, 8) == 5
        assert replicas_for_slo(0.0, 25.0, 0.5, 8) == 0
        # Cap respected even when the SLO is unreachable.
        assert replicas_for_slo(1000.0, 25.0, 0.5, 6) == 6


# ----------------------------------------------------------------------
# Autoscaler
# ----------------------------------------------------------------------

class TestAutoscaler:
    def _scaler(self, **kw):
        return Autoscaler(AutoscalerConfig(**kw))

    def test_scale_up_is_immediate(self):
        s = self._scaler()
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0) == 1
        assert s.target_replicas(150.0, 25.0, 0.5, 8, 120.0) >= 7

    def test_scale_down_waits_for_patience(self):
        s = self._scaler(scale_down_patience=2)
        assert s.target_replicas(150.0, 25.0, 0.5, 8, 120.0) >= 7
        high = s.committed
        # One quiet round: held at the committed level.
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0) == high
        # Second consecutive quiet round: commit the lower target.
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0) == 1

    def test_pending_down_tracks_highest_demand(self):
        """Scaling below a level the patience window still demanded
        would violate the SLO there — the pending target is the MAX."""
        s = self._scaler(scale_down_patience=2)
        s.target_replicas(150.0, 25.0, 0.5, 8, 120.0)
        s.target_replicas(10.0, 25.0, 0.5, 8, 120.0)    # pending 1
        # Demand recovers mid-window to 4-replica level; commit must
        # not drop below it.
        assert s.target_replicas(80.0, 25.0, 0.5, 8, 120.0) >= 4

    def test_scale_to_zero_threshold(self):
        s = self._scaler(min_requests_per_round=5.0, scale_down_patience=1)
        assert s.target_replicas(0.01, 25.0, 0.5, 8, 120.0) == 0
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0) == 1

    def test_unknown_config_key_rejected(self):
        with pytest.raises(ValueError, match="unknown serving"):
            AutoscalerConfig.from_dict({"headrom": 1.2})


# ----------------------------------------------------------------------
# Trace-level serving job class
# ----------------------------------------------------------------------

class TestServingTrace:
    def test_command_round_trip(self):
        cmd = serving_command(base_rps=8, peak_rps=16, period_s=14400,
                              tokens_per_request=64,
                              decode_tokens_per_s=1600, max_replicas=12,
                              spikes=((2400.0, 1200.0, 10.0),))
        params = parse_serving_command(cmd)
        assert params["base_rps"] == 8.0
        assert params["max_replicas"] == 12
        assert params["spikes"] == ((2400.0, 1200.0, 10.0),)
        assert serving_service_rate(cmd) == pytest.approx(25.0)

    def test_malformed_spike_raises(self):
        with pytest.raises(ValueError, match="spike_at"):
            parse_serving_command("serve.py --spike_at 10:20")

    def test_trace_line_round_trip(self, tmp_path):
        svc = make_serving_job(base_rps=5, peak_rps=10, period_s=3600,
                               lifetime_s=1800, slo_p99_s=0.25)
        line = job_to_trace_line(svc, 42.0)
        path = tmp_path / "t.trace"
        path.write_text(line + "\n")
        jobs, arrivals = parse_trace(str(path))
        assert arrivals == [42.0]
        assert is_serving_job(jobs[0])
        assert jobs[0].SLO == pytest.approx(0.25)
        assert jobs[0].duration == 1800
        assert parse_serving_command(jobs[0].command)["peak_rps"] == 10.0

    def test_committed_mixed_trace_parses(self):
        jobs, arrivals = parse_trace(os.path.join(DATA,
                                                  "serving_mixed.trace"))
        serving = [j for j in jobs if is_serving_job(j)]
        assert len(serving) == 2
        assert len(jobs) - len(serving) == 10
        # simulate() admits in file order gated on the head arrival, so
        # the committed trace must be arrival-sorted.
        assert arrivals == sorted(arrivals)


# ----------------------------------------------------------------------
# Mixed-trace simulation
# ----------------------------------------------------------------------

def run_mixed_sim(jobs, arrivals, cluster=8, policy="max_min_fairness",
                  serving_config=None, shockwave_config=None,
                  profiles=None, round_s=120.0):
    sched = Scheduler(
        get_policy(policy, seed=0), simulate=True,
        throughputs_file=THROUGHPUTS, profiles=profiles,
        config=SchedulerConfig(time_per_iteration=round_s, seed=0,
                               serving=serving_config,
                               shockwave=shockwave_config))
    makespan = sched.simulate({"v100": cluster}, arrivals, jobs)
    return sched, makespan


class TestMixedSimulation:
    def test_spike_preempts_training_and_holds_slo(self):
        """The acceptance scenario: a 10x spike must scale serving up
        (preempting training chips) while p99 SLO attainment stays
        above 99%, and training must finish afterwards."""
        trainings = [train_job(steps=30000, duration=3000)
                     for _ in range(6)]
        svc = make_serving_job(
            base_rps=10.0, peak_rps=20.0, period_s=14400.0,
            lifetime_s=7200.0, slo_p99_s=0.5, tokens_per_request=64,
            decode_tokens_per_s=1600.0, max_replicas=8,
            spikes=((2400.0, 1200.0, 10.0),))
        jobs = trainings + [svc]
        arrivals = [0.0] * len(jobs)
        sched, makespan = run_mixed_sim(jobs, arrivals, cluster=8)

        summary = sched.serving_summary()
        assert summary is not None
        svc_stats = summary["services"][0]
        assert svc_stats["slo_attainment"] > 0.99
        assert svc_stats["peak_replicas"] >= 6      # 10x spike scale-up
        assert svc_stats["retired"]

        # Training preemption: during spike rounds serving holds most
        # of the 8 chips, so fewer training jobs run than before.
        tier_svc = list(sched._serving_tier.services.values())[0]
        training_ids = set(range(6))

        def training_in_round(r):
            return sum(1 for k in sched.rounds.per_round_schedule[r]
                       if k in training_ids)
        spike_rounds = [h["round"] for h in tier_svc.history
                        if h["assigned"] >= 6]
        calm_rounds = [h["round"] for h in tier_svc.history
                       if h["assigned"] <= 2 and h["round"] < 15]
        assert spike_rounds, "spike never scaled serving to >= 6 chips"
        assert calm_rounds
        assert max(training_in_round(r) for r in spike_rounds) < \
            max(training_in_round(r) for r in calm_rounds)

        # Training still completes (all 6 jobs) after the spike.
        assert sched.get_num_completed_jobs() == 7  # 6 training + svc
        assert makespan >= 7200.0

    def test_scale_to_zero_at_trough_and_recovery(self):
        """A trough-starting service must hold zero replicas (chips all
        back to training), then scale up as the day-curve rises, and
        retire at end of life."""
        svc = make_serving_job(
            base_rps=0.0, peak_rps=8.0, period_s=28800.0,
            lifetime_s=7200.0, slo_p99_s=1.0, tokens_per_request=64,
            decode_tokens_per_s=1600.0, max_replicas=3)
        jobs = [train_job(steps=30000, duration=3000), svc]
        sched, _ = run_mixed_sim(
            jobs, [0.0, 0.0], cluster=4,
            serving_config={"min_requests_per_round": 5.0})
        tier_svc = list(sched._serving_tier.services.values())[0]
        stats = tier_svc.summary()
        assert stats["rounds_at_zero_replicas"] >= 3
        assert stats["peak_replicas"] >= 1          # scaled back up
        assert stats["retired"]
        assert stats["slo_attainment"] > 0.99
        # While at zero, no replica jobs existed — nothing occupied
        # chips on serving's behalf.
        zero_rounds = [h for h in tier_svc.history if h["assigned"] == 0]
        assert zero_rounds and all(h["target"] == 0 for h in zero_rounds)

    def test_serving_only_trace_completes(self):
        """No training at all: the round loop must keep rolling for the
        service (including through zero-replica rounds) and terminate
        at its end of life."""
        svc = make_serving_job(base_rps=5.0, peak_rps=10.0,
                               period_s=7200.0, lifetime_s=3600.0,
                               slo_p99_s=0.5)
        sched, makespan = run_mixed_sim([svc], [0.0], cluster=2)
        assert sched.serving_summary()["services"][0]["retired"]
        assert makespan >= 3600.0

    def test_training_only_trace_keeps_tier_inert(self):
        jobs = [train_job(), train_job(steps=20000, duration=2000)]
        sched, _ = run_mixed_sim(jobs, [0.0, 0.0], cluster=2)
        assert sched._serving_tier is None
        assert sched.serving_summary() is None
        assert sched._serving_job_ids == set()

    def test_shockwave_planner_sees_shrunk_capacity(self):
        """Mixed trace under the shockwave policy: the MILP's capacity
        row shrinks by the serving reservation, training FTF stays in
        the paper's envelope, and serving holds its SLO."""
        from shockwave_tpu.core.metrics import unfair_fraction
        from shockwave_tpu.core.oracle import read_throughputs
        from shockwave_tpu.core.profiles import build_profiles
        trainings = [train_job(steps=30000, duration=3000)
                     for _ in range(4)]
        svc = make_serving_job(
            base_rps=10.0, peak_rps=20.0, period_s=14400.0,
            lifetime_s=4800.0, slo_p99_s=0.5, tokens_per_request=64,
            decode_tokens_per_s=1600.0, max_replicas=6,
            spikes=((1200.0, 1200.0, 8.0),))
        jobs = trainings + [svc]
        profiles = build_profiles(jobs, read_throughputs(THROUGHPUTS))
        assert profiles[-1] is None                 # serving slot
        sched, _ = run_mixed_sim(
            jobs, [0.0] * len(jobs), cluster=8, policy="shockwave",
            shockwave_config={"num_gpus": 8, "future_rounds": 8,
                              "time_per_iteration": 120.0},
            profiles=profiles)
        assert sched.serving_summary()["slo_attainment"] > 0.99
        assert sched.get_num_completed_jobs() == 5
        # The planner saw the shrunk capacity row at spike time.
        tier_svc = list(sched._serving_tier.services.values())[0]
        assert max(h["assigned"] for h in tier_svc.history) >= 5
        ftf_static, _ = sched.get_finish_time_fairness()
        assert len(ftf_static) == 4                 # training only
        # Paper envelope: Fig-9 shockwave reports <= ~7% unfair jobs at
        # rho > 1.1; a co-scheduled spike must not blow through it.
        assert unfair_fraction(ftf_static) <= 0.25

    def test_late_training_arrival_after_scale_up(self):
        """Regression: replica spawns must not consume trace-job id
        slots — a training job arriving AFTER a serving scale-up must
        still bind its own positional profile under shockwave (and the
        trace-resume cursor must ignore replicas)."""
        from shockwave_tpu.core.oracle import read_throughputs
        from shockwave_tpu.core.profiles import build_profiles
        from shockwave_tpu.sched.scheduler import SERVING_REPLICA_ID_BASE
        svc = make_serving_job(
            base_rps=10.0, peak_rps=20.0, period_s=14400.0,
            lifetime_s=3600.0, slo_p99_s=0.5, max_replicas=4)
        late_train = train_job(steps=20000, duration=2000)
        jobs = [svc, late_train]            # training arrives at t=600
        profiles = build_profiles(jobs, read_throughputs(THROUGHPUTS))
        sched, _ = run_mixed_sim(
            jobs, [0.0, 600.0], cluster=6, policy="shockwave",
            shockwave_config={"num_gpus": 6, "future_rounds": 8,
                              "time_per_iteration": 120.0},
            profiles=profiles)
        # The late training job got int id 1 (its trace position), not
        # an id displaced by the replicas spawned before it arrived.
        assert sched.get_num_completed_jobs() == 2
        assert sched.num_jobs_submitted == 2    # resume cursor: trace only
        assert all(j.integer_job_id() >= SERVING_REPLICA_ID_BASE
                   for j in sched._serving_job_ids)
        assert sched.get_average_jct()[3]       # training JCT recorded

    def test_serving_rounds_accounted_across_idle_gap(self):
        """Regression: with a live service and a far-future arrival,
        the simulator must walk the gap round by round (autoscaler
        consulted, SLO accounted) instead of leaping the clock to the
        arrival."""
        svc = make_serving_job(base_rps=5.0, peak_rps=10.0,
                               period_s=7200.0, lifetime_s=3600.0,
                               slo_p99_s=0.5, max_replicas=2)
        late_train = train_job(steps=5000, duration=600)
        sched, _ = run_mixed_sim([svc, late_train], [0.0, 3000.0],
                                 cluster=2)
        tier_svc = list(sched._serving_tier.services.values())[0]
        # 3600 s lifetime / 120 s rounds: every window accounted.
        assert tier_svc.rounds_total >= 29
        assert tier_svc.requests_offered > 0
        assert sched.get_num_completed_jobs() == 2

    def test_cluster_fraction_caps_aggregate_reservation(self):
        """Regression: max_cluster_fraction bounds ALL services
        together, and a zero budget yields zero replicas (no max(1,..)
        floor)."""
        svc_a = make_serving_job(base_rps=50.0, peak_rps=100.0,
                                 period_s=0.0, lifetime_s=2400.0,
                                 slo_p99_s=0.5, max_replicas=8)
        svc_b = make_serving_job(base_rps=50.0, peak_rps=100.0,
                                 period_s=0.0, lifetime_s=2400.0,
                                 slo_p99_s=0.5, max_replicas=8)
        sched, _ = run_mixed_sim(
            [svc_a, svc_b, train_job(steps=10000, duration=1000)],
            [0.0, 0.0, 0.0], cluster=8,
            serving_config={"max_cluster_fraction": 0.5})
        for h_a, h_b in zip(*[s.history for s in
                              sched._serving_tier.services.values()]):
            assert h_a["assigned"] + h_b["assigned"] <= 4
        # Zero budget: a fraction that rounds to 0 chips must scale
        # nothing (the operator said "no serving capacity").
        scaler = Autoscaler(AutoscalerConfig())
        assert scaler.target_replicas(100.0, 25.0, 0.5, 0, 120.0) == 0

    def test_deterministic_replay_bit_identical(self):
        """Same mixed trace, two runs: schedules and serving accounting
        must match exactly (the tier is a pure function of the trace)."""
        def once():
            trainings = [train_job(steps=20000, duration=2000)
                         for _ in range(3)]
            svc = make_serving_job(
                base_rps=10.0, peak_rps=20.0, period_s=7200.0,
                lifetime_s=3600.0, slo_p99_s=0.5,
                spike_seed=3, num_spikes=1, spike_mult=10.0,
                spike_duration_s=600.0, max_replicas=6)
            sched, makespan = run_mixed_sim(
                trainings + [svc], [0.0] * 4, cluster=6)
            tier_svc = list(sched._serving_tier.services.values())[0]
            return (makespan, sched.rounds.per_round_schedule,
                    tier_svc.history, tier_svc.summary())
        assert once() == once()


# ----------------------------------------------------------------------
# Durability: serving state through the journal
# ----------------------------------------------------------------------

@pytest.mark.recovery
class TestServingJournalReplay:
    def test_services_and_replicas_survive_replay(self, tmp_path):
        from shockwave_tpu.sched.journal import DurabilityLayer, load_state
        trainings = [train_job(steps=20000, duration=2000)]
        svc = make_serving_job(base_rps=10.0, peak_rps=20.0,
                               period_s=7200.0, lifetime_s=2400.0,
                               slo_p99_s=0.5, max_replicas=4)
        sched = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=120.0, seed=0))
        layer = DurabilityLayer(str(tmp_path))
        sched.attach_durability(layer)
        sched.simulate({"v100": 4}, [0.0, 0.0], trainings + [svc])
        layer.close()

        fresh = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=120.0, seed=0))
        fresh.restore_from_durable_state(load_state(str(tmp_path)))
        tier = fresh._serving_tier
        assert tier is not None
        assert len(tier.services) == 1
        replayed = list(tier.services.values())[0]
        assert replayed.retired                    # serving_retired event
        assert not replayed.replicas               # all removed via journal
        assert fresh._serving_job_ids              # replicas were adopted
        assert not fresh.acct.jobs                 # everything completed

    def test_snapshot_pickles_tier_and_rebinds(self):
        import pickle
        svc = make_serving_job(base_rps=5.0, peak_rps=10.0,
                               period_s=7200.0, lifetime_s=1200.0,
                               slo_p99_s=0.5)
        sched, _ = run_mixed_sim([train_job(), svc], [0.0, 0.0], cluster=2)
        snap = pickle.loads(pickle.dumps(sched.snapshot_state()))
        assert snap["_serving_tier"]._sched is None   # dropped for pickling
        fresh = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=120.0, seed=0))
        fresh.restore_state(snap)
        assert fresh._serving_tier._sched is fresh    # re-bound
        assert fresh.serving_summary()["services"]


# ----------------------------------------------------------------------
# KV-cache decoder parity
# ----------------------------------------------------------------------

class TestDecoderParity:
    def test_cached_decode_matches_full_forward(self):
        import jax
        import jax.numpy as jnp

        from shockwave_tpu.models.decoder import DecoderLM, greedy_decode
        model = DecoderLM(dim=64, num_layers=2, num_heads=4, mlp_dim=128,
                          max_len=32)
        rng = jax.random.PRNGKey(0)
        prompt = jax.random.randint(rng, (2, 4), 0, 256, dtype=jnp.int32)
        params = model.init(rng, prompt)
        gen = greedy_decode(model, params, prompt, num_tokens=6)
        # Oracle: full causal forward re-run per generated token.
        tokens = prompt
        oracle = []
        for _ in range(6):
            logits = model.apply(params, tokens)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(
                jnp.int32)[:, None]
            oracle.append(nxt)
            tokens = jnp.concatenate([tokens, nxt], axis=1)
        assert (gen == jnp.concatenate(oracle, axis=1)).all()


# ----------------------------------------------------------------------
# Physical loopback: a serving replica through the lease machinery
# ----------------------------------------------------------------------

def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestServingReplicaLease:
    def test_replica_holds_and_renews_lease(self):
        """A serving service submitted to a REAL PhysicalScheduler: the
        tier spawns a replica, the replica is dispatched through the
        normal round machinery (serve.py command + --replica_of
        markers), holds a lease, RENEWS it mid-round, and reports
        progress (requests served) — all under SWTPU_SANITIZE=1 (the
        conftest runtime fixture asserts a clean concurrency report)."""
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        from shockwave_tpu.sched.physical import PhysicalScheduler

        sched_port = free_port()
        worker_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=3),
            expected_num_workers=2, port=sched_port)

        dispatched_commands = []
        renewals = []

        class ServingStub:
            """Worker daemon stub mimicking serve.py's lease protocol:
            init, one mid-round renewal, then done with requests
            served."""

            def __init__(self):
                self._client = WorkerToSchedulerClient(
                    "localhost", sched_port)
                self.server = serve_worker(worker_port, {
                    "RunJob": self._run_job, "KillJob": lambda j: None,
                    "Reset": lambda: None, "Shutdown": lambda: None,
                })
                self.worker_ids, self.round_duration = (
                    self._client.register_worker(
                        "v5e", "127.0.0.1", worker_port, 2))

            def _run_job(self, jobs, worker_id, round_id):
                def execute():
                    try:
                        for j in jobs:
                            dispatched_commands.append(j["command"])
                            it = IteratorToSchedulerClient(
                                j["job_id"], worker_id, "localhost",
                                sched_port)
                            it.init()
                            time.sleep(0.3)
                            grant = it.update_lease(
                                steps=10, duration=0.3,
                                max_steps=j["num_steps"],
                                max_duration=1e9)
                            renewals.append((j["job_id"], grant))
                        time.sleep(0.5)
                        self._client.notify_done(
                            [j["job_id"] for j in jobs], worker_id,
                            [25] * len(jobs), [0.8] * len(jobs))
                    except Exception:  # noqa: BLE001 - teardown race
                        pass
                threading.Thread(target=execute, daemon=True).start()

            def stop(self):
                self.server.stop(grace=0)

        worker = ServingStub()
        try:
            svc = make_serving_job(
                base_rps=10.0, peak_rps=10.0, period_s=0.0,
                lifetime_s=3600.0, slo_p99_s=0.5, tokens_per_request=64,
                decode_tokens_per_s=1600.0, max_replicas=1)
            service_id = sched.add_job(svc)
            assert service_id == JobIdPair(0)
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                with sched._lock:
                    served = any(
                        steps > 0
                        for job_id in sched._serving_job_ids
                        for steps in [sched.acct.total_steps_run.get(
                            job_id, 0)])
                if served and renewals:
                    break
                time.sleep(0.2)
            assert dispatched_commands, "no replica was ever dispatched"
            assert all("serve.py" in c and "--replica_of 0" in c
                       for c in dispatched_commands)
            assert renewals, "replica never renewed its lease"
            # The renewal granted the replica the rest of its budget.
            job_id, grant = renewals[0]
            assert grant[0] > 0
            with sched._lock:
                assert sched._serving_tier is not None
                tier_svc = list(sched._serving_tier.services.values())[0]
                assert tier_svc.replicas, "replica not on the books"
                assert served, "no requests-served progress recorded"
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)


# ----------------------------------------------------------------------
# The real replica workload under a real lease
# ----------------------------------------------------------------------

@pytest.mark.timeout(180)
class TestServeWorkloadLease:
    def test_serve_py_decodes_until_lease_expiry(self, tmp_path):
        """workloads/serving/serve.py as a subprocess against a stub
        scheduler: the KV-cache decode loop must run under the
        LeaseIterator, consume exactly its granted step budget
        (requests served), and exit cooperatively."""
        import subprocess
        import sys as _sys

        from conftest import cpu_subprocess_env
        from shockwave_tpu.runtime.servers import serve_scheduler

        port = free_port()
        granted_steps = 12
        server = serve_scheduler(port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": lambda job_id: (granted_steps, 1e6, 0.0),
            # Renewals keep the grant unchanged -> lease is final.
            "UpdateLease": lambda job_id, worker_id, steps, duration,
            max_steps, max_duration: (int(max_steps), float(max_duration),
                                      0.0, 1e9),
            "UpdateResourceRequirement": lambda *a: None,
        })
        env = cpu_subprocess_env()
        env.update({
            "SWTPU_JOB_ID": "0", "SWTPU_WORKER_ID": "0",
            "SWTPU_ROUND_ID": "0", "SWTPU_SCHED_ADDR": "localhost",
            "SWTPU_SCHED_PORT": str(port),
        })
        script = os.path.join(REPO, "shockwave_tpu", "workloads",
                              "serving", "serve.py")
        try:
            out = subprocess.run(
                [_sys.executable, script, "--batch_size", "1",
                 "--tokens_per_request", "8", "--model_dim", "32",
                 "--model_layers", "1", "--model_heads", "2",
                 "--prompt_len", "4", "--checkpoint_dir", str(tmp_path),
                 "--enable_lease_iterator"],
                capture_output=True, text=True, timeout=150, env=env)
        finally:
            server.stop(grace=0)
        assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
        assert f"SERVED {granted_steps} request batches" in out.stdout, \
            out.stdout[-2000:]


# ----------------------------------------------------------------------
# Hardened TPU evidence capture (reproduce/tpu/liveness_probe.py)
# ----------------------------------------------------------------------

class TestLivenessProbe:
    def _probe(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "liveness_probe",
            os.path.join(REPO, "reproduce", "tpu", "liveness_probe.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_live_backend_passes(self):
        probe = self._probe()
        assert probe.probe_backend(snippet="pass", timeout_s=60) is None

    def test_init_failure_bounded_retries(self):
        probe = self._probe()
        sleeps = []
        err = probe.probe_backend(
            attempts=3, backoff_s=7.0,
            snippet="import sys; sys.stderr.write('boom'); sys.exit(1)",
            sleep=sleeps.append)
        assert err is not None and "boom" in err
        assert sleeps == [7.0, 7.0]     # attempts-1 backoffs, then stop

    def test_wedged_backend_times_out_bounded(self):
        probe = self._probe()
        start = time.time()
        err = probe.probe_backend(
            attempts=2, timeout_s=0.5, backoff_s=0.1,
            snippet="import time; time.sleep(60)")
        assert err is not None and "timed out" in err
        assert time.time() - start < 10     # hard-bounded, never hangs

    def test_cli_exit_codes(self, capsys):
        probe = self._probe()
        probe.PROBE_SNIPPET = "pass"
        assert probe.main(["--attempts", "1", "--timeout", "60"]) == 0

    def test_bench_degrades_to_last_good_evidence(self, monkeypatch):
        """A failing probe must NOT poison the bench row with tpu_error
        when committed evidence exists — it degrades to the last-good
        file, provenance-marked (the BENCH_r05 regression)."""
        import importlib.util
        import sys as _sys
        probe_dir = os.path.join(REPO, "reproduce", "tpu")
        if probe_dir not in _sys.path:
            _sys.path.insert(0, probe_dir)
        import liveness_probe
        spec = importlib.util.spec_from_file_location(
            "swtpu_bench", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        monkeypatch.setattr(liveness_probe, "probe_backend",
                            lambda **kw: "backend liveness probe timed "
                                         "out (wedged accelerator "
                                         "tunnel?)")
        out = bench.tpu_phase()
        assert "tpu_error" not in out
        assert out["tpu_probe"].startswith("skipped:")
        assert out.get("tpu_source", "").startswith("reproduce/tpu/")
        # ...and with no committed evidence at all, the error IS the row.
        monkeypatch.setattr(bench, "committed_tpu_result", lambda: {})
        out = bench.tpu_phase()
        assert "tpu_error" in out


# ----------------------------------------------------------------------
# Config plumbing
# ----------------------------------------------------------------------

class TestServingConfigPlumbing:
    def test_serving_mixed_config_parses(self):
        with open(os.path.join(REPO, "configs", "serving_mixed.json")) as f:
            config = json.load(f)
        AutoscalerConfig.from_dict(config["serving"])

    def test_obs_catalog_has_serving_metrics(self):
        from shockwave_tpu.obs import names
        serving_specs = [s for s in names.all_metric_specs()
                         if s.name.startswith("swtpu_serving_")]
        assert len(serving_specs) >= 6
        assert any(s.name == "swtpu_serving_p99_seconds"
                   for s in serving_specs)


# ----------------------------------------------------------------------
# Measured serving path (serving/measured.py + obs/quantiles.py)
# ----------------------------------------------------------------------

class TestArrivalClock:
    def _load(self, rps=20.0):
        return DiurnalLoad(rps, rps, 0.0)

    def test_seeded_and_deterministic(self):
        from shockwave_tpu.serving.measured import ArrivalClock
        a = list(ArrivalClock(self._load(), 42, 50.0))
        b = list(ArrivalClock(self._load(), 42, 50.0))
        assert a == b and a == sorted(a)
        assert list(ArrivalClock(self._load(), 43, 50.0)) != a
        # Poisson sanity: ~20 rps over 50 s.
        assert 700 <= len(a) <= 1300

    def test_round_robin_split_partitions_stream(self):
        """Every replica's share, unioned, is exactly the service's
        arrival stream — no request lost or duplicated by the split."""
        from shockwave_tpu.serving.measured import ArrivalClock
        full = list(ArrivalClock(self._load(), 7, 30.0))
        shares = [list(ArrivalClock(self._load(), 7, 30.0,
                                    replica_index=r, num_replicas=3))
                  for r in range(3)]
        assert sorted(t for share in shares for t in share) == full
        assert all(shares)

    def test_spiky_curve_respects_rate_bound(self):
        """Thinning against the static bound must stay correct under
        concurrent spikes (the bound sweeps spike boundaries)."""
        from shockwave_tpu.serving.measured import ArrivalClock
        load = DiurnalLoad(5.0, 10.0, 1000.0,
                           spikes=[Spike(10.0, 50.0, 4.0),
                                   Spike(30.0, 50.0, 2.0)])
        arrivals = list(ArrivalClock(load, 3, 200.0))
        in_spike = [t for t in arrivals if 30.0 <= t < 60.0]
        calm = [t for t in arrivals if 100.0 <= t < 130.0]
        assert len(in_spike) > 2 * len(calm)


class TestReplicaMeter:
    def test_latency_is_queueing_plus_service(self):
        from shockwave_tpu.serving.measured import ReplicaMeter
        meter = ReplicaMeter(iter([0.0, 0.0, 10.0]), batch_size=1,
                             tokens_per_request=4)
        assert meter.step(1.0) == 1          # t in [0, 1): no wait
        assert meter.step(1.0) == 1          # queued 1 s + 1 s service
        delta = meter.take_delta()
        assert delta["requests"] == 2 and delta["tokens"] == 8
        from shockwave_tpu.obs.quantiles import QuantileSketch
        sketch = QuantileSketch.from_payload(delta["sketch"])
        # Latencies 1.0, 2.0: p99 covers the queued request.
        assert sketch.quantile(0.99) >= 2.0

    def test_fast_chip_idles_instead_of_serving_the_future(self):
        """The service clock can never outrun the measured wall: the
        t=10 arrival is NOT served until 10 s of wall have actually
        been measured (the 860k-fictitious-samples regression from the
        first physical drive)."""
        from shockwave_tpu.serving.measured import ReplicaMeter
        meter = ReplicaMeter(iter([0.0, 10.0]), batch_size=1,
                             tokens_per_request=4)
        assert meter.step(1.0) == 1
        for _ in range(8):
            assert meter.step(1.0) == 0      # idle: t=10 is the future
        assert not meter.exhausted           # still one queued arrival
        assert meter.step(1.0) == 1          # wall reached t=10
        assert meter.step(1.0) == 0
        assert meter.exhausted

    def test_idle_jump_is_explicit_and_virtual_only(self):
        """The calibration driver owns its timeline and may jump idle
        gaps; the jump serves nothing and charges no busy time."""
        from shockwave_tpu.serving.measured import ReplicaMeter
        meter = ReplicaMeter(iter([0.0, 10.0]), batch_size=1,
                             tokens_per_request=1)
        assert meter.idle_to_next_arrival()
        assert meter.step(1.0) == 1
        assert meter.idle_to_next_arrival()  # wall jumps to t=10
        assert meter.wall == pytest.approx(10.0)
        assert meter.step(1.0) == 1          # zero queueing delay
        assert not meter.idle_to_next_arrival()
        delta = meter.take_delta()
        assert delta["busy_s"] == pytest.approx(2.0)

    def test_batch_admits_only_arrived_requests(self):
        from shockwave_tpu.serving.measured import ReplicaMeter
        meter = ReplicaMeter(iter([0.0, 0.0, 0.1, 5.0]), batch_size=8,
                             tokens_per_request=1)
        assert meter.step(0.5) == 2          # t=0.1 and t=5 are future
        assert meter.step(0.5) == 1          # t=0.1 arrived by t=0.5
        assert meter.step(0.5) == 0          # t=5 still in the future

    def test_busy_and_span_accounting(self):
        from shockwave_tpu.serving.measured import ReplicaMeter
        meter = ReplicaMeter(iter([0.0, 10.0]), batch_size=1,
                             tokens_per_request=1)
        meter.step(1.0)
        meter.idle_to_next_arrival()
        meter.step(1.0)
        delta = meter.take_delta()
        assert delta["busy_s"] == pytest.approx(2.0)
        assert delta["span_s"] == pytest.approx(11.0)
        assert meter.take_delta() is None


class TestMeasuredReportWire:
    def test_round_trip_through_log_lines(self):
        from shockwave_tpu.serving.measured import (encode_report,
                                                    find_reports)
        delta = {"v": 1, "sketch": {"v": 1, "b": [[10, 3]], "n": 3,
                                    "s": 0.5},
                 "requests": 3, "tokens": 12, "busy_s": 0.2,
                 "span_s": 0.3}
        blob = ("[ts] [PROGRESS] [STEPS] 3\n"
                "[ts] [SERVING] [MEASURED] " + encode_report(delta)
                + "\n[ts] [LEASE] [EXPIRED] done")
        assert find_reports(blob) == [delta]

    def test_malformed_and_foreign_lines_skipped(self):
        from shockwave_tpu.serving.measured import (MEASURED_REPORT_MARKER,
                                                    find_reports)
        lines = [MEASURED_REPORT_MARKER + "{not json",
                 MEASURED_REPORT_MARKER + '{"v": 99}',
                 "plain progress line"]
        assert find_reports(lines) == []

    def test_encode_is_byte_deterministic(self):
        from shockwave_tpu.serving.measured import encode_report
        delta = {"b": 1, "a": 2, "sketch": {"n": 0}}
        assert encode_report(dict(sorted(delta.items()))) == \
            encode_report(dict(reversed(sorted(delta.items()))))


class TestServiceMeasuredState:
    def test_prior_fallback_and_convergence(self):
        from shockwave_tpu.serving.measured import (ReplicaMeter,
                                                    ServiceMeasuredState)
        st = ServiceMeasuredState(mu_analytic=25.0, tokens_per_request=4,
                                  mu_prior_weight=10.0)
        assert st.mu_estimate() == 25.0      # exact analytic fallback
        # Replica actually serves at 10 req/s (0.1 s per 1-batch step).
        meter = ReplicaMeter(iter([i * 0.05 for i in range(400)]),
                             batch_size=1, tokens_per_request=4)
        while meter.step(0.1):
            pass
        st.ingest(meter.take_delta())
        assert 9.5 < st.mu_estimate() < 11.5   # pulled to measurement
        assert st.measured_tokens_per_s() == pytest.approx(40.0)

    def test_window_drain_semantics(self):
        from shockwave_tpu.serving.measured import (ReplicaMeter,
                                                    ServiceMeasuredState)
        st = ServiceMeasuredState(20.0, 2)
        meter = ReplicaMeter(iter([0.0, 0.1]), 1, 2)
        meter.step(0.1), meter.step(0.1)
        st.ingest(meter.take_delta())
        window = st.take_window()
        assert window["requests"] == 2 and window["p99_s"] > 0
        assert st.take_window() is None      # drained
        assert st.requests_total == 2        # cumulative survives


class TestAutoscalerMeasuredEscalation:
    def test_measured_breach_beats_analytic_model(self):
        """The committed pool meets the analytic SLO but measurement
        says otherwise: the target must escalate one above the level
        that produced the breach."""
        s = Autoscaler(AutoscalerConfig())
        base = s.target_replicas(10.0, 25.0, 0.5, 8, 120.0)
        assert base == 1
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0,
                                 measured_p99_s=1.2) == 2
        # Healthy measurement: no escalation beyond the analytic need.
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0,
                                 measured_p99_s=0.1) == 2  # patience
        assert s.target_replicas(10.0, 25.0, 0.5, 8, 120.0,
                                 measured_p99_s=0.1) == 1

    def test_no_measurement_is_bit_identical(self):
        a, b = Autoscaler(AutoscalerConfig()), Autoscaler(AutoscalerConfig())
        for rate in (10.0, 150.0, 5.0, 5.0, 80.0):
            assert a.target_replicas(rate, 25.0, 0.5, 8, 120.0) == \
                b.target_replicas(rate, 25.0, 0.5, 8, 120.0,
                                  measured_p99_s=None)

    def test_escalation_respects_cap(self):
        s = Autoscaler(AutoscalerConfig())
        s.target_replicas(10.0, 25.0, 0.5, 1, 120.0)
        assert s.target_replicas(10.0, 25.0, 0.5, 1, 120.0,
                                 measured_p99_s=9.9) == 1


class TestMeasuredTierIntegration:
    def _load_driver(self):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serving_measured_calibration",
            os.path.join(REPO, "scripts", "drivers",
                         "serving_measured_calibration.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_sim_mode_never_measures(self):
        """Simulation must never exercise the measured path: no sketch
        samples, mu exactly the analytic value, no measured gauges —
        the bit-identity guarantee for canonical replays."""
        from shockwave_tpu.obs import names as obs_names
        svc = make_serving_job(base_rps=5.0, peak_rps=10.0,
                               period_s=7200.0, lifetime_s=2400.0,
                               slo_p99_s=0.5)
        sched, _ = run_mixed_sim([train_job(), svc], [0.0, 0.0],
                                 cluster=2)
        tier_svc = list(sched._serving_tier.services.values())[0]
        assert tier_svc.measured.requests_total == 0
        assert tier_svc.mu == tier_svc.mu_analytic
        assert tier_svc.last_measured_window is None
        rendered = sched.obs.registry.render_prometheus()
        assert 'swtpu_serving_measured_p99_seconds{service="0"}' \
            not in rendered

    def test_ingest_refines_mu_and_drives_scaling(self):
        """Tier-level measured loop in one process: ingest a breach
        delta as the Done fold would, account a round, and watch the
        NEXT round's target escalate past the analytic model, with the
        measured gauges exported."""
        import numpy as np

        from shockwave_tpu.obs import names as obs_names
        from shockwave_tpu.serving.load import DiurnalLoad as DL
        from shockwave_tpu.serving.measured import (ArrivalClock,
                                                    ReplicaMeter)
        svc_job = make_serving_job(
            base_rps=2.0, peak_rps=2.0, period_s=0.0, lifetime_s=36000.0,
            slo_p99_s=0.5, tokens_per_request=64,
            decode_tokens_per_s=1600.0, max_replicas=4)
        sched, _ = run_mixed_sim(
            [svc_job], [0.0], cluster=4,
            serving_config={"measured_min_samples": 1,
                            "mu_prior_weight": 16.0})
        # Fresh tier walk, post-sim (the sim itself stayed analytic).
        tier = sched._serving_tier
        svc = list(tier.services.values())[0]
        assert svc.mu == svc.mu_analytic

        # One replica measured a breach: overloaded queue at HALF the
        # declared service rate.
        rng = np.random.RandomState(5)
        meter = ReplicaMeter(ArrivalClock(DL(40.0, 40.0, 0.0), 5, 30.0),
                             1, 64)
        while meter.step(float(rng.exponential(2.0 / 25.0))):
            pass
        delta = meter.take_delta()
        # The sim ran the service to retirement; rebind one replica id
        # the way adopt_replica would for a live dispatch.
        replica_id = JobIdPair(4300000)
        tier._replica_service[replica_id.integer_job_id()] = svc.int_id
        tier.ingest_measured(replica_id, delta)
        assert svc.measured.requests_total == delta["requests"]
        assert svc.mu < svc.mu_analytic          # refined downward

        window = svc.measured.take_window()
        svc.last_measured_window = window
        assert window["p99_s"] > svc.slo_p99_s
        measured = svc.measured_p99_for_scaling(1)
        assert measured == window["p99_s"]
        committed = svc.autoscaler.committed
        target = svc.autoscaler.target_replicas(
            2.0, svc.mu, svc.slo_p99_s, 4, 120.0,
            measured_p99_s=measured)
        assert target >= max(committed, 1) + 1 or target == 4

    def test_malformed_delta_is_dropped_not_fatal(self):
        svc_job = make_serving_job(base_rps=2.0, peak_rps=2.0,
                                   period_s=0.0, lifetime_s=36000.0,
                                   slo_p99_s=0.5, max_replicas=2)
        sched, _ = run_mixed_sim([svc_job], [0.0], cluster=2)
        tier = sched._serving_tier
        svc = list(tier.services.values())[0]
        replica_id = JobIdPair(4300001)
        tier._replica_service[replica_id.integer_job_id()] = svc.int_id
        tier.ingest_measured(replica_id, {"v": 1, "sketch": {"v": 7}})
        assert svc.measured.requests_total == 0
        # Unknown replica: silently ignored.
        tier.ingest_measured(JobIdPair(999999), {"v": 1})

    def test_calibration_envelope(self):
        """Measured p99 must sit inside the committed calibration
        envelope of the analytic model at single-replica load levels,
        and mu must be recovered within 5%."""
        import argparse
        mod = self._load_driver()
        args = argparse.Namespace(
            mu=20.0, horizon_s=600.0, batch_size=1,
            tokens_per_request=64, mu_prior_weight=64.0, seed=11)
        for rho in (0.4, 0.8):
            row = mod.calibration_row(rho, 1, args)
            assert row["samples"] > 0
            assert row["merge_order_independent"]
            assert 0.7 <= row["p99_ratio"] <= 2.0, row
            assert abs(row["mu_estimate"] / 20.0 - 1.0) < 0.05, row
        # Multi-replica: round-robin dispatch is measurably WORSE than
        # the central-queue M/M/c idealization — the calibration gap
        # the measured loop exists to close.
        row = mod.calibration_row(0.6, 4, args)
        assert row["p99_ratio"] > 1.5, row


class TestSaturationGaugeExposition:
    def test_saturated_service_drops_p99_and_flags(self):
        """Satellite regression: a saturated service must NOT keep
        exporting its last healthy p99 forever — the series is dropped
        and swtpu_serving_saturated{...} = 1 replaces it."""
        # max_replicas=1 against an impossible load: permanently
        # saturated after the first accounted round.
        svc = make_serving_job(base_rps=500.0, peak_rps=500.0,
                               period_s=0.0, lifetime_s=1200.0,
                               slo_p99_s=0.1, tokens_per_request=64,
                               decode_tokens_per_s=1600.0,
                               max_replicas=1)
        sched, _ = run_mixed_sim([svc], [0.0], cluster=2)
        rendered = sched.obs.registry.render_prometheus()
        assert 'swtpu_serving_saturated{service="0"} 1' in rendered
        assert 'swtpu_serving_p99_seconds{service="0"}' not in rendered

    def test_healthy_service_exports_p99_and_zero_flag(self):
        svc = make_serving_job(base_rps=5.0, peak_rps=5.0,
                               period_s=0.0, lifetime_s=1200.0,
                               slo_p99_s=0.5, tokens_per_request=64,
                               decode_tokens_per_s=1600.0,
                               max_replicas=4)
        sched, _ = run_mixed_sim([svc], [0.0], cluster=4)
        rendered = sched.obs.registry.render_prometheus()
        assert 'swtpu_serving_saturated{service="0"} 0' in rendered
        assert 'swtpu_serving_p99_seconds{service="0"}' in rendered


# ----------------------------------------------------------------------
# Physical loopback: measured telemetry drives a real scaling decision
# ----------------------------------------------------------------------

@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestMeasuredPhysicalLoopback:
    def test_measured_p99_drives_scale_up(self):
        """The acceptance loopback: a REAL PhysicalScheduler + stub
        worker exchange measured sketch deltas over the live gRPC Done
        path; the measured p99 breach (at half the declared service
        rate) must drive a scale-up the analytic model alone would not
        make, and the mu estimate must pull away from the analytic
        prior — all sanitizer-clean (the runtime marker's fixture
        fails the test on any lock-order or ownership report)."""
        import argparse
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serving_measured_calibration",
            os.path.join(REPO, "scripts", "drivers",
                         "serving_measured_calibration.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        args = argparse.Namespace(
            seed=11, throughputs=THROUGHPUTS)
        outcome = mod.run_loopback(args)
        assert outcome == {
            "measured_samples_reported": True,
            "measured_p99_exported": True,
            "measured_drove_scale_up": True,
            "mu_refined": True,
            "analytic_only_target": 1,
        }


class TestReplicaCommandMeasuredFlags:
    def test_spawn_carries_lifetime_and_phase(self):
        """The replica's measured clock needs the service lifetime
        (seeded-spike placement matches the analytic model) and the
        service-relative spawn offset (mid-life replicas measure the
        current load, not the t=0 trough) — both appended at spawn."""
        svc_job = make_serving_job(base_rps=5.0, peak_rps=10.0,
                                   period_s=7200.0, lifetime_s=2400.0,
                                   slo_p99_s=0.5, max_replicas=2)
        sched, _ = run_mixed_sim([svc_job], [0.0], cluster=2)
        tier = sched._serving_tier
        svc = list(tier.services.values())[0]
        # Exercise the spawn path directly post-sim (the sim's own
        # replicas completed and were removed with the retired service).
        svc.retired = False
        before = set(sched.acct.jobs)
        tier._spawn_replica(svc)
        new_ids = set(sched.acct.jobs) - before
        assert new_ids
        cmd = sched.acct.jobs[new_ids.pop()].command
        params = parse_serving_command(cmd)
        assert float(params["service_lifetime_s"]) == 2400.0
        # Spawned at end-of-sim: the offset is the service-relative now.
        assert float(params["arrival_phase_s"]) >= 0.0
