"""Analytic per-service queueing model: (offered load, replicas) -> latency.

Each replica is one autoregressive decode server working through a
shared request queue, so a service with `c` replicas is modeled as an
M/M/c queue: Poisson arrivals at rate ``lam`` (the diurnal/bursty curve
from serving/load.py), exponential-ish service at rate ``mu`` per
replica (``decode_tokens_per_s / tokens_per_request`` — the O(1)
KV-cached decode cost model makes per-request service time essentially
length-proportional, PAPERS.md 2603.09555). Latency quantiles come from
Erlang-C:

    P(wait > t) = C(c, lam/mu) * exp(-(c*mu - lam) * t)

so the q-quantile of sojourn time is the service time plus
``ln(C / (1-q)) / (c*mu - lam)`` when C > 1-q. Everything is a pure
closed-form function of (lam, c, mu): the simulator, the autoscaler and
the SLO-attainment accounting all evaluate the same deterministic
numbers, which is what makes mixed-trace replays bit-identical.
"""
from __future__ import annotations

import math

#: Sentinel latency of a saturated (or empty) replica pool under load.
SATURATED = float("inf")


def erlang_c(c: int, offered: float) -> float:
    """Probability an arrival must queue in an M/M/c with offered load
    ``offered = lam/mu`` Erlangs. 1.0 at/over saturation, 0.0 with no
    load. Computed with the standard iterative recurrence (numerically
    stable for the replica counts a chip pool can hold)."""
    if offered <= 0.0:
        return 0.0
    if c <= 0 or offered >= c:
        return 1.0
    # inv_b is 1/B(k, offered) of the Erlang-B recurrence.
    inv_b = 1.0
    for k in range(1, c + 1):
        inv_b = 1.0 + inv_b * k / offered
    blocking = 1.0 / inv_b
    rho = offered / c
    return blocking / (1.0 - rho + rho * blocking)


def latency_quantile(lam: float, replicas: int, mu: float,
                     q: float) -> float:
    """q-quantile of request sojourn time (wait + service), seconds.

    SATURATED when the pool cannot keep up (lam >= c*mu) — every queue
    length diverges — and plain service time when there is no load."""
    if mu <= 0.0:
        raise ValueError(f"service rate mu must be positive, got {mu}")
    if not 0.0 < q < 1.0:
        raise ValueError(f"quantile must be in (0, 1), got {q}")
    service = 1.0 / mu
    if lam <= 0.0:
        return service
    if replicas <= 0 or lam >= replicas * mu:
        return SATURATED
    p_queue = erlang_c(replicas, lam / mu)
    if p_queue <= (1.0 - q):
        return service
    wait = math.log(p_queue / (1.0 - q)) / (replicas * mu - lam)
    return service + wait


def p50_latency(lam: float, replicas: int, mu: float) -> float:
    return latency_quantile(lam, replicas, mu, 0.5)


def p99_latency(lam: float, replicas: int, mu: float) -> float:
    return latency_quantile(lam, replicas, mu, 0.99)


def replicas_for_slo(lam: float, mu: float, slo_p99_s: float,
                     max_replicas: int) -> int:
    """Smallest replica count whose p99 meets the SLO at arrival rate
    ``lam``, capped at ``max_replicas`` (best effort when even the cap
    cannot meet it). 0 when there is no load to serve."""
    if lam <= 0.0:
        return 0
    if max_replicas <= 0:
        return 0
    for c in range(max(1, math.ceil(lam / mu)), max_replicas + 1):
        if p99_latency(lam, c, mu) <= slo_p99_s:
            return c
    return max_replicas


def mu_from_tokens_per_s(tokens_per_s: float,
                         tokens_per_request: int) -> float:
    """Per-replica service rate (requests/s) from a measured decode
    throughput — the measured-path counterpart of
    ``core/trace.serving_service_rate`` (which reads the declared
    decode rate off the trace command). 0.0 when nothing was measured."""
    if tokens_per_request <= 0 or tokens_per_s <= 0.0:
        return 0.0
    return tokens_per_s / tokens_per_request


__all__ = ["SATURATED", "erlang_c", "latency_quantile", "p50_latency",
           "p99_latency", "replicas_for_slo", "mu_from_tokens_per_s"]
