"""LeaseIterator: the job-side cooperative-preemption runtime for JAX.

Wraps a training input pipeline; each `next()` accounts one step against a
scheduler-granted lease and renews the lease at 75% consumption. When the
lease expires the iterator raises StopIteration so the training loop can
checkpoint and exit; the worker daemon then reports progress back.

TPU-native notes (vs the reference's GavelIterator, gavel_iterator.py):
- JAX dispatch is async: wall-clock per step lies unless we synchronize.
  The iterator syncs on the caller-provided `sync_ref` (usually the
  last step's loss) only at lease-check boundaries — block_until_ready
  plus a one-scalar device_get, which provably waits even through a
  relayed chip — so honest timing costs one device sync per lease
  check, not per step.
- Async dispatch also lets the Python loop run arbitrarily far ahead of
  the device, which breaks the lease protocol in two ways: the step
  counter races to its renewal threshold in seconds while no compute
  has finished, and the lease-boundary sync then has to drain the
  whole dispatched backlog in one blocking call — minutes for
  slow-step models — during which no renewal RPC (= heartbeat) is
  sent, so the scheduler kills the job as unresponsive. The iterator
  therefore bounds run-ahead with a sliding window of sync refs,
  drained in batches: once SWTPU_RUNAHEAD_STEPS (default 8) extra
  steps are queued past the window, it blocks on the oldest batch's
  newest ref — one device round trip per `runahead` steps (amortized
  for relayed chips), free when the device keeps up, an honest short
  wait when it doesn't, keeping run-ahead under 2x the window.
- Multi-chip jobs synchronize their exit with a global barrier across
  hosts so a gang checkpoint is consistent.
- Checkpointing is delegated to caller functions (orbax-based helpers in
  models/checkpoint.py).

Environment contract (set by the dispatcher):
  SWTPU_JOB_ID, SWTPU_WORKER_ID, SWTPU_ROUND_ID, SWTPU_SCHED_ADDR,
  SWTPU_SCHED_PORT
"""
from __future__ import annotations

import atexit
import collections
import logging
import os
import time
from typing import Any, Callable, Iterable, Optional

from ..obs import names as obs_names
from . import spans as spans_mod
from .clients import IteratorToSchedulerClient
from .lease import Lease

INFINITY = 1e9
LEASE_UPDATE_FRACTION = 0.75
LOG_FORMAT = "[{asctime}] [{event}] [{status}] {message}"
DATE_FORMAT = "%Y-%m-%d %H:%M:%S"


def _device_sync(value: Any) -> None:
    """Block until device work producing `value` is complete.

    block_until_ready alone is not sufficient on relayed accelerator
    backends (it can return before remote execution finishes), so also
    materialize one scalar on the host — a device_get provably waits
    (core/timing.py documents the measurement behind this)."""
    if value is None:
        return
    try:
        import jax
    except ImportError:
        return
    try:
        jax.block_until_ready(value)
        from ..core.timing import fetch_scalar
        fetch_scalar(value)
    except Exception as e:  # noqa: BLE001
        # Sync is load-bearing twice over: honest durations AND the
        # run-ahead bound that keeps renewal heartbeats timely. On
        # persistent failure both degrade — say exactly that.
        logging.getLogger("lease_iterator").warning(
            "device sync failed (%s: %s); step timing may under-report "
            "and the async run-ahead bound is not enforced (renewal "
            "heartbeats may be late; scheduler may kill this job as "
            "unresponsive)", type(e).__name__, e)


class LeaseIterator:
    def __init__(self, data_loader: Iterable, checkpoint_dir: str,
                 load_checkpoint_func: Callable, save_checkpoint_func: Callable,
                 synthetic_data: bool = False, write_on_close: bool = True,
                 distributed_barrier: Optional[Callable] = None,
                 gang_allreduce: Optional[Callable] = None,
                 gang_sync_every: int = 16):
        """gang_allreduce(value, op) -> float ("max"/"min" across the
        gang) makes every time-based decision step-deterministic for
        multi-process gangs: lease grants are agreed by min at grant
        time, the running duration is agreed by max at `gang_sync_every`
        step boundaries, and time-based expiry/renewal checks only fire
        at those boundaries — so all members take identical control
        paths at identical steps and a member can never enter the exit
        barrier while a peer is still issuing training collectives.
        Steps-based checks are deterministic already (server-side
        first-requester-computes consensus)."""
        self._data_loader = data_loader
        self._load_checkpoint_func = load_checkpoint_func
        self._save_checkpoint_func = save_checkpoint_func
        # Batch caching is only sound when the loader itself is
        # synthetic; gate here (the loader is in hand) so no caller can
        # collapse a real dataset to one cached batch by passing the
        # CLI flag through unguarded.
        self._synthetic_data = (synthetic_data
                                and getattr(data_loader, "synthetic", True))
        self._distributed_barrier = distributed_barrier
        self._gang_allreduce = gang_allreduce
        self._gang_sync_every = max(int(gang_sync_every), 1)
        # Absolute agreed-duration threshold for the next time-triggered
        # renewal (gang mode replaces the per-step countdown, which
        # drifts epsilon-differently on every member's local clock).
        self._renewal_duration_threshold = INFINITY

        self._job_id = int(os.environ["SWTPU_JOB_ID"])
        self._worker_id = int(os.environ["SWTPU_WORKER_ID"])
        self._round_id = int(os.environ["SWTPU_ROUND_ID"])
        sched_addr = os.environ["SWTPU_SCHED_ADDR"]
        sched_port = int(os.environ["SWTPU_SCHED_PORT"])

        round_dir = os.path.join(checkpoint_dir, ".swtpu",
                                 f"round={self._round_id}")
        os.makedirs(round_dir, exist_ok=True)
        self._log_file = os.path.join(round_dir,
                                      f"worker={self._worker_id}.log")
        self._init_logger()

        # Fleet tracing (opt-in): continue the dispatch's trace inside
        # this training process. The dispatcher exports the launch
        # span's context + the shard directory into the environment
        # (runtime/spans.py); the `trainer` span covers this dispatch's
        # whole lease window and is closed (with the step count) at
        # lease expiry / completion / process exit, whichever first.
        self._span_shard = spans_mod.shard_from_env(role="trainer")
        self._trainer_span = None
        self._trainer_ctx = None
        if self._span_shard is not None:
            self._trainer_span = self._span_shard.open_span(
                obs_names.SPAN_TRAINER, parent=spans_mod.from_environ(),
                job=self._job_id, worker=self._worker_id,
                round=self._round_id)
            # Kept past the span's close: the post-lease checkpoint
            # save (the one every dispatch performs) still parents its
            # ckpt-save span here.
            self._trainer_ctx = self._trainer_span.context
            atexit.register(self._close_trainer_span)

        self._rpc = IteratorToSchedulerClient(
            self._job_id, self._worker_id, sched_addr, sched_port)

        self._steps = 0
        self._duration = 0.0
        self._done = False
        # Gray-failure drill hook (runtime/faults.py `degrade` rules):
        # the dispatcher exports SWTPU_DEGRADE_FACTOR when an injected
        # slowdown covers this dispatch, and the iterator honors it by
        # padding each step to compute_time / factor — the process
        # stays fully live (renewals, heartbeats, checkpoints) while
        # its step rate drops to `factor` of normal, exactly the
        # straggler the scheduler's health layer must catch.
        try:
            self._degrade_factor = min(max(float(
                os.environ.get("SWTPU_DEGRADE_FACTOR", "") or 1.0),
                1e-3), 1.0)
        except ValueError:
            self._degrade_factor = 1.0
        self._last_degrade_sleep = 0.0
        self._sync_ref: Any = None
        # Sliding window bounding async run-ahead (module docstring).
        self._runahead = max(
            int(os.environ.get("SWTPU_RUNAHEAD_STEPS", "8")), 1)
        self._sync_window: "collections.deque" = collections.deque()
        self._last_windowed_ref: Any = None
        self._steps_without_new_ref = 0
        self._warned_static_ref = False
        self._cached_batch = None
        self._lease = Lease(0, 0)
        self._write_on_close = write_on_close
        #: Measured-serving telemetry lines awaiting the next renewal.
        self._measured_buffer: list = []
        atexit.register(self._close_log)
        if write_on_close:
            atexit.register(self._write_info)
        # LIFO: flushes before the log handler closes above.
        atexit.register(self._flush_measured_to_log)
        self._update_lease(init=True)
        self._write_info()
        # Start the clock at construction: shared-filesystem reads before the
        # first step can take tens of seconds and must count against the lease.
        self._prev_time = time.time()

    # -- iteration ---------------------------------------------------------

    def __iter__(self):
        self._iterator = iter(self._data_loader)
        return self

    def __len__(self):
        return len(self._data_loader)

    def set_sync_ref(self, value: Any) -> None:
        """Give the iterator a device value (e.g. the last loss) to sync on
        when honest timing is needed."""
        self._sync_ref = value

    def log_measurement(self, payload: str) -> None:
        """Append one measured-telemetry line to the iterator log. The
        worker daemon ships the whole log back on the Done heartbeat,
        so this is the job->scheduler telemetry channel that needs no
        new RPC field — serving replicas use it for their request-
        latency sketch deltas (serving/measured.py wire format; the
        scheduler's log fold routes marked lines to the serving tier
        instead of the job timeline)."""
        self._logger.info(payload, extra={"event": "SERVING",
                                          "status": "MEASURED"})

    def queue_measurement(self, payload: str) -> None:
        """Buffer one measured-telemetry line for the NEXT lease
        renewal (UpdateLeaseRequest.measured_reports): a sticky serving
        replica can hold one extended lease for its whole life, so
        renewals — not Done — are its per-round channel. Whatever was
        never shipped on a renewal is flushed to the iterator log at
        exit and arrives with Done instead; the consumer dedupes by
        the payload's (round, seq), so double delivery is harmless."""
        self._measured_buffer.append(payload)

    def _flush_measured_to_log(self) -> None:
        """Exit path: unsent measured telemetry rides the Done report's
        log channel (at-exit, and idempotent — the buffer drains)."""
        buffered, self._measured_buffer = self._measured_buffer, []
        for payload in buffered:
            self.log_measurement(payload)

    def __next__(self):
        now = time.time()
        if self._prev_time is None:
            self._prev_time = now
        elapsed = now - self._prev_time
        self._duration += elapsed
        self._prev_time = now

        if self._degrade_factor < 1.0:
            # Injected slowdown: pad the step by compute/factor -
            # compute. The previous pad is subtracted from `elapsed`
            # first, or each round's pad would compound on the last
            # one's instead of on the real compute time.
            compute = max(elapsed - self._last_degrade_sleep, 0.0)
            pause = compute * (1.0 / self._degrade_factor - 1.0)
            if pause > 0:
                time.sleep(pause)
                self._last_degrade_sleep = pause
                slept_until = time.time()
                self._duration += slept_until - self._prev_time
                elapsed += slept_until - self._prev_time
                self._prev_time = slept_until
            else:
                self._last_degrade_sleep = 0.0

        gang = self._gang_allreduce is not None
        if not gang:
            # Bound async run-ahead: enqueue the newest sync ref (the
            # previous step's loss) and block on the ref from
            # `runahead` steps back. Free when the device keeps up;
            # otherwise an honest wait that keeps the step counter,
            # the duration clock, and the dispatched backlog within
            # `runahead` steps of the device — so lease checks fire on
            # time and a lease-boundary sync never has to drain a
            # minutes-deep queue while heartbeats are due. (Gangs get
            # the same bound from their gang_sync_every boundary sync.)
            if (self._sync_ref is not None
                    and self._sync_ref is not self._last_windowed_ref):
                self._sync_window.append(self._sync_ref)
                self._last_windowed_ref = self._sync_ref
                self._steps_without_new_ref = 0
            else:
                # Without a fresh per-step ref the window cannot grow
                # and the run-ahead bound silently disappears — warn
                # once so the caller knows to set_sync_ref every step.
                self._steps_without_new_ref += 1
                if (self._steps_without_new_ref > 2 * self._runahead
                        and not self._warned_static_ref):
                    self._warned_static_ref = True
                    self._logger.warning(
                        "no fresh sync ref for %d steps: async run-ahead "
                        "is unbounded and lease timing/heartbeats may "
                        "degrade; call set_sync_ref(loss) every step",
                        self._steps_without_new_ref)
            if len(self._sync_window) >= 2 * self._runahead:
                # Steps execute in dispatch order (the donated train
                # state chains them), so syncing the newest ref of the
                # drained batch proves everything before it finished:
                # one device round trip per `runahead` steps — amortized
                # for relayed backends where each host fetch costs tens
                # of ms — with run-ahead in [runahead, 2*runahead).
                newest_drained = None
                while len(self._sync_window) > self._runahead:
                    newest_drained = self._sync_window.popleft()
                _device_sync(newest_drained)
                sync_now = time.time()
                waited = sync_now - self._prev_time
                self._duration += waited
                elapsed += waited  # feeds the renewal countdown below
                self._prev_time = sync_now
        # Gang members only evaluate time-based conditions at shared
        # K-step boundaries, on an agreed (max-allreduced) duration, so
        # the whole gang reaches the same verdict at the same step.
        boundary = (not gang) or (self._steps % self._gang_sync_every == 0)
        if gang and boundary:
            _device_sync(self._sync_ref)
            sync_now = time.time()
            self._duration += sync_now - self._prev_time
            self._prev_time = sync_now
            self._duration = max(
                self._duration,
                float(self._gang_allreduce(self._duration, "max")))

        time_renewal_due = boundary and (
            self._duration >= self._renewal_duration_threshold if gang
            else self._time_until_lease_update <= 0)
        if self._steps_until_lease_update <= 0 or time_renewal_due:
            # Sync outstanding device work so self._duration is honest at the
            # renewal boundary.
            _device_sync(self._sync_ref)
            sync_now = time.time()
            self._duration += sync_now - self._prev_time
            self._prev_time = sync_now
            self._update_lease()

        if ((boundary and self._duration >= self._lease.max_duration)
                or self._steps >= self._lease.max_steps):
            self._done = True
            self._logger.info(
                "%d / %s steps, %.4f / %.4f seconds",
                self._steps, self._lease.max_steps, self._duration,
                self._lease.max_duration,
                extra={"event": "LEASE", "status": "EXPIRED"})
            _device_sync(self._sync_ref)
            self._close_trainer_span()
            if self._distributed_barrier is not None:
                self._distributed_barrier()
            raise StopIteration

        try:
            if self._synthetic_data and self._cached_batch is not None:
                value = self._cached_batch
            else:
                value = next(self._iterator)
                if self._synthetic_data:
                    self._cached_batch = value
            self._steps += 1
        except StopIteration:
            self._write_info()
            raise

        if self._synthetic_data and self._steps % len(self._data_loader) == 0:
            raise StopIteration

        self._steps_until_lease_update -= 1
        self._time_until_lease_update -= elapsed
        return value

    # -- job-side API ------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done

    def complete(self, timeout: bool = False) -> None:
        self._done = True
        if not self._write_on_close:
            self._write_info()
        self._close_trainer_span()
        self._logger.info("", extra={"event": "LEASE", "status": "COMPLETE"})

    def report_checkpoint_ahead(self) -> None:
        """The restored checkpoint already satisfies the job's FULL step
        budget although this dispatch ran 0 steps: the previous worker
        died after the checkpoint was saved but before its progress
        report reached the scheduler (the failed-in-round synthesis
        reports 0 steps). The scheduler's missing delta is exactly what
        it granted this dispatch (remaining = total - its own count), so
        reporting the initial lease grant reconverges its accounting
        with the durable checkpoint — instead of exiting (0, 0), the
        micro-task-failure signal, every round until the job is dropped.
        """
        self._steps = int(self._lease.max_steps)
        self._duration = max(self._duration, time.time() - self._prev_time,
                             1e-3)
        self._done = True
        self._logger.info(
            "checkpoint already at budget; reporting granted remainder %d",
            self._steps, extra={"event": "LEASE", "status": "CKPT_AHEAD"})

    def update_resource_requirement(self, big_bs: bool, small_bs: bool) -> None:
        """Report a batch-size change request; job must checkpoint + exit."""
        self._done = True
        self._rpc.update_resource_requirement(big_bs, small_bs)

    def _ckpt_span(self, name):
        """Checkpoint spans nest under the trainer span's context —
        which outlives the span's close, because the standard flow is
        lease expiry (span closed) THEN save_checkpoint. No-op context
        without a shard."""
        from contextlib import nullcontext
        if self._span_shard is None or self._trainer_ctx is None:
            return nullcontext()
        return self._span_shard.span(name, parent=self._trainer_ctx,
                                     job=self._job_id)

    def _close_trainer_span(self) -> None:
        """Close (once) the dispatch-lifetime trainer span with the
        final step count; runs at lease exit and again harmlessly from
        atexit for crashed/aborted loops."""
        if self._span_shard is None or self._trainer_span is None:
            return
        span, self._trainer_span = self._trainer_span, None
        self._span_shard.close_span(span, steps=self._steps,
                                    done=self._done)

    def load_checkpoint(self, *args, **kwargs):
        self._logger.info("", extra={"event": "LOAD CHECKPOINT", "status": "BEGIN"})
        with self._ckpt_span(obs_names.SPAN_CKPT_LOAD):
            out = self._load_checkpoint_func(*args, **kwargs)
        self._logger.info("", extra={"event": "LOAD CHECKPOINT", "status": "END"})
        return out

    def save_checkpoint(self, *args, **kwargs):
        self._logger.info("", extra={"event": "SAVE CHECKPOINT", "status": "BEGIN"})
        with self._ckpt_span(obs_names.SPAN_CKPT_SAVE):
            out = self._save_checkpoint_func(*args, **kwargs)
        self._logger.info("", extra={"event": "SAVE CHECKPOINT", "status": "END"})
        return out

    # -- lease protocol ----------------------------------------------------

    def _update_lease(self, init: bool = False) -> None:
        if init:
            max_steps, max_duration, extra_time = self._rpc.init()
        else:
            # Piggyback buffered measured-serving telemetry on the
            # renewal; cleared only after the RPC returned (a failed
            # renewal keeps the deltas for the next attempt / the
            # exit-path log flush — the consumer dedupes by seq).
            shipping = list(self._measured_buffer)
            max_steps, max_duration, run_time_so_far, deadline = (
                self._rpc.update_lease(self._steps, self._duration,
                                       self._lease.max_steps,
                                       self._lease.max_duration,
                                       measured_reports=shipping or None))
            del self._measured_buffer[:len(shipping)]
            extra_time = 0.0
            if self._duration + run_time_so_far > deadline:
                # Deadline enforcement: scheduler says we have overrun 1.5x
                # our expected duration; finish now. Gang members reach
                # this with agreed durations at the same step, so all
                # exit together; the barrier keeps the gang checkpoint
                # consistent either way.
                self._logger.info(
                    "over deadline (%.1f + %.1f > %.1f)", self._duration,
                    run_time_so_far, deadline,
                    extra={"event": "LEASE", "status": "DEADLINE"})
                if self._distributed_barrier is not None:
                    self._distributed_barrier()
                self.complete(timeout=True)
                raise StopIteration

        if self._gang_allreduce is not None:
            # Agree the grant across the gang (min is the safe direction:
            # nobody outruns a peer's lease). Steps are already identical
            # via the scheduler's first-requester-computes consensus;
            # durations can differ by RPC-arrival epsilons.
            max_steps = int(self._gang_allreduce(max_steps, "min"))
            max_duration = float(self._gang_allreduce(max_duration, "min"))
            extra_time = float(self._gang_allreduce(extra_time, "min"))

        # Plan the next renewal at LEASE_UPDATE_FRACTION of the new grant; an
        # unchanged grant means this lease is final.
        if max_steps == self._lease.max_steps:
            self._steps_until_lease_update = INFINITY
        else:
            additional = max_steps - self._lease.max_steps
            left = self._lease.max_steps - self._steps
            self._steps_until_lease_update = (
                left + additional * LEASE_UPDATE_FRACTION)
        if max_duration <= self._lease.max_duration:
            self._time_until_lease_update = INFINITY
            self._renewal_duration_threshold = INFINITY
        else:
            additional = max_duration - self._lease.max_duration
            left = self._lease.max_duration - self._duration
            self._time_until_lease_update = (
                left + additional * LEASE_UPDATE_FRACTION + extra_time)
            self._renewal_duration_threshold = (
                self._duration + self._time_until_lease_update)

        self._lease.max_steps = max_steps
        self._lease.max_duration = max_duration + extra_time

    # -- logging -----------------------------------------------------------

    def _init_logger(self):
        self._logger = logging.getLogger(f"lease_iterator.{self._job_id}")
        self._logger.propagate = False
        self._logger.setLevel(logging.DEBUG)
        self._file_handler = logging.FileHandler(self._log_file)
        self._file_handler.setFormatter(
            logging.Formatter(LOG_FORMAT, datefmt=DATE_FORMAT, style="{"))
        self._logger.addHandler(self._file_handler)

    def _write_info(self):
        self._logger.info("%d", self._steps,
                          extra={"event": "PROGRESS", "status": "STEPS"})
        self._logger.info("%f", self._duration,
                          extra={"event": "PROGRESS", "status": "DURATION"})

    def _close_log(self):
        self._logger.removeHandler(self._file_handler)
        self._file_handler.close()


# Alias for users migrating from the reference framework.
GavelIterator = LeaseIterator
