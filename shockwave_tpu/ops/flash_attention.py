"""Fused flash attention as a Pallas TPU kernel.

Forward pass is a blocked online-softmax kernel: the grid walks
(batch*heads, q-block, k-block) with the k-block dimension innermost, so
the f32 accumulator and running max/normalizer live in VMEM scratch
across k-steps and the full (T x T) score matrix never materializes in
HBM. Scores hit the MXU via `jnp.dot(..., preferred_element_type=f32)`.

Backward is two Pallas kernels using the standard flash-attention
gradient formulas (Dao et al. '22): a dq pass (k-blocks innermost, the
forward's grid layout) and a dk/dv pass (q-blocks innermost), each
accumulating in VMEM scratch. The forward emits a per-row logsumexp
residual (`lse`, (BH, Tq, 8)-tiled) so the backward recovers
p = exp(s - lse) without re-running the online softmax; every matmul
runs bf16 operands with f32 accumulation to stay on the MXU's native
path, and causal k/q-blocks past the diagonal skip their FLOPs in both
passes.

The single-chip complement to parallel/ring_attention.py (which shards
the sequence across chips); the reference has no attention kernel at all
(vanilla torch softmax attention, workloads/pytorch/translation/
transformer/SubLayers.py).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128
SUBLANES = 8  # f32 tile height: mask/bias operands pad to this


def _fa_kernel(q_ref, k_ref, v_ref, kbias_ref, o_ref, lse_ref, m_scr, l_scr,
               acc_scr, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # With causal masking, k-blocks strictly above the diagonal contribute
    # nothing; skip their FLOPs entirely.
    should_run = True
    if causal:
        should_run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]  # (block_q, d)
        k = k_ref[0]  # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        # Key-padding bias: kbias_ref is a (1, SUBLANES, block_k) tile of
        # 0.0 (attend) / NEG_INF (masked), replicated across sublanes so
        # the block meets Mosaic's (8, 128) tiling; reduce one row out.
        s = s + jnp.max(kbias_ref[0], axis=0, keepdims=True)

        m_prev = m_scr[:, :1]                       # (block_q, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                      # (block_q, block_k)
        correction = jnp.exp(m_prev - m_new)        # (block_q, 1)
        l_new = l_scr[:, :1] * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:, :1]  # (block_q, 1)
        o_ref[0] = (acc_scr[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # Per-row logsumexp residual for the backward kernels: p can then
        # be recovered as exp(s - lse) without re-running the online
        # softmax. Stored (block_q, SUBLANES)-tiled — same broadcast
        # pattern as the m/l scratch, no in-kernel transpose needed.
        lse = m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _pad_axis(x, axis: int, to: int):
    pad = (-x.shape[axis]) % to
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _kbias(kv_mask, bh, tk):
    """Mosaic requires operand blocks whose last two dims tile to (8, 128),
    so the (BH, Tk) key mask travels as a (BH, SUBLANES, Tk) f32 additive
    bias (0 = attend, NEG_INF = masked), replicated across sublanes —
    shared by the forward and both backward kernels so the masking
    encoding cannot drift between them."""
    bias = jnp.where(kv_mask > 0, 0.0, NEG_INF).astype(jnp.float32)
    return jnp.broadcast_to(bias[:, None, :], (bh, SUBLANES, tk))


def _forward_impl(q, k, v, kv_mask, scale, causal, block_q, block_k,
                  interpret):
    """q: (BH, Tq, D); k,v: (BH, Tk, D); kv_mask: (BH, Tk) int8."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    grid = (bh, nq, nk)

    kbias = _kbias(kv_mask, bh, tk)

    kernel = functools.partial(
        _fa_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, block_k), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, SUBLANES), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, SUBLANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v, kbias)
    return out, lse


def _dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, kbias_ref,
               dq_ref, dq_scr, *, scale: float, causal: bool, block_q: int,
               block_k: int):
    """dQ: grid (BH, q-block, k-block), k innermost (forward's layout);
    dq accumulates in VMEM scratch across k-steps."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    should_run = True
    if causal:
        should_run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]                        # (block_q, d)
        k = k_ref[0]                        # (block_k, d)
        v = v_ref[0]
        g = g_ref[0]                        # (block_q, d)
        lse = lse_ref[0][:, :1]             # (block_q, 1) f32
        delta = delta_ref[0][:, :1]         # (block_q, 1) f32
        # bf16 operands + f32 accumulation on every matmul (the Dao et
        # al. recipe): f32 x f32 would fall off the MXU's native path.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = s + jnp.max(kbias_ref[0], axis=0, keepdims=True)
        # Masked/causal-excluded entries sit at the NEG_INF floor; so does
        # lse for a FULLY masked row (no visible key), where exp(s - lse)
        # would become O(1) garbage that leaks into valid keys' dk/dv.
        # Zero them explicitly (the standard flash backward guard).
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, kbias_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                causal: bool, block_q: int, block_k: int):
    """dK/dV: grid (BH, k-block, q-block), q innermost; dk/dv accumulate
    in VMEM scratch across q-steps."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    should_run = True
    if causal:
        # q-blocks strictly above the diagonal see none of this k-block.
        should_run = ki * block_k <= qi * block_q + (block_q - 1)

    @pl.when(should_run)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        g = g_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = s + jnp.max(kbias_ref[0], axis=0, keepdims=True)
        # Same fully-masked-row guard as _dq_kernel.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(
            g, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        p16 = p.astype(q.dtype)
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            p16, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _backward_impl(q, k, v, kv_mask, out, lse, g, scale, causal, block_q,
                   block_k, interpret):
    """Flash-attention gradients as two Pallas kernels (Dao et al.): a dq
    pass (k innermost, forward's grid layout) and a dk/dv pass (q
    innermost), both reading the forward's per-row logsumexp residual
    instead of re-running the online softmax."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    g16 = g.astype(q.dtype)

    kbias = _kbias(kv_mask, bh, tk)
    # delta_i = rowsum(dO_i * O_i), stored (BH, Tq, SUBLANES)-tiled like
    # the lse residual so the kernels index both identically.
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None], (bh, tq, SUBLANES))

    def spec_q(index):
        return pl.BlockSpec((1, block_q, d), index, memory_space=pltpu.VMEM)

    def spec_k(index):
        return pl.BlockSpec((1, block_k, d), index, memory_space=pltpu.VMEM)

    def spec_row(index):
        return pl.BlockSpec((1, block_q, SUBLANES), index,
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nq, nk),
        in_specs=[
            spec_q(lambda b, i, j: (b, i, 0)),
            spec_k(lambda b, i, j: (b, j, 0)),
            spec_k(lambda b, i, j: (b, j, 0)),
            spec_q(lambda b, i, j: (b, i, 0)),
            spec_row(lambda b, i, j: (b, i, 0)),
            spec_row(lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, SUBLANES, block_k), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=spec_q(lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g16, lse, delta, kbias)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(bh, nk, nq),
        in_specs=[
            spec_q(lambda b, j, i: (b, i, 0)),
            spec_k(lambda b, j, i: (b, j, 0)),
            spec_k(lambda b, j, i: (b, j, 0)),
            spec_q(lambda b, j, i: (b, i, 0)),
            spec_row(lambda b, j, i: (b, i, 0)),
            spec_row(lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, SUBLANES, block_k), lambda b, j, i: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            spec_k(lambda b, j, i: (b, j, 0)),
            spec_k(lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g16, lse, delta, kbias)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_bhtd(q, k, v, kv_mask, scale, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, _ = _forward_impl(q, k, v, kv_mask, scale, causal, block_q,
                           block_k, interpret)
    return out


def _flash_bhtd_fwd(q, k, v, kv_mask, scale, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _forward_impl(q, k, v, kv_mask, scale, causal, block_q,
                             block_k, interpret)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bhtd_bwd(scale, causal, block_q, block_k, residuals, g):
    q, k, v, kv_mask, out, lse = residuals
    interpret = jax.default_backend() != "tpu"
    dq, dk, dv = _backward_impl(q, k, v, kv_mask, out, lse, g, scale,
                                causal, block_q, block_k, interpret)
    return dq, dk, dv, None


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    key_padding_mask: Optional[jnp.ndarray] = None,
                    scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024):
    """Fused attention for (batch, seq, heads, head_dim) inputs.

    head_dim is zero-padded to a multiple of 8 sublanes when ragged; it
    is NOT padded to the 128-lane tile — a full-coverage lane dim is
    legal in Mosaic and skipping the pad saves bandwidth (measured ~5%
    at d=64). Default blocks are large (1024) because per-grid-step
    overhead dominates on real v5e hardware: at (4, 2048, 8, 64) causal
    bf16, blocks of 1024 run 5.7x faster than blocks of 128 and 3.6x
    faster than the einsum path (0.47 ms vs 1.68 ms). Sequence lengths
    must be divisible by the block size (shrunk to T for short
    sequences); mask ragged sequences upstream. key_padding_mask is
    (B, Tk) with True = attend. Cross-attention (Tq != Tk) is supported
    for causal=False. Runs the Pallas TPU kernel on TPU and the Pallas
    interpreter elsewhere (tests/CI on CPU).
    """
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if causal and tq != tk:
        raise ValueError("causal flash attention requires Tq == Tk")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"flash_attention requires seq lens divisible by the block "
            f"size; got Tq={tq}, Tk={tk}, blocks=({block_q}, {block_k})")

    def to_bhtd(x):
        t = x.shape[1]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, t, -1)
        return _pad_axis(x, 2, SUBLANES)

    qf, kf, vf = to_bhtd(q), to_bhtd(k), to_bhtd(v)
    if key_padding_mask is None:
        kv_mask = jnp.ones((b, tk), jnp.int8)
    else:
        kv_mask = key_padding_mask.astype(jnp.int8)  # (B, Tk), 1 = attend
    kv_mask = jnp.repeat(kv_mask, h, axis=0)  # (B*H, Tk), head-major rows
    out = _flash_bhtd(qf, kf, vf, kv_mask, float(scale), causal,
                      block_q, block_k)
    out = out[:, :tq, :d].reshape(b, h, tq, d)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
