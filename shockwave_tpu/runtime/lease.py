"""A lease bounds how many steps / how long a job may run before it must
checkpoint and yield (reference: scheduler/lease.py)."""
from dataclasses import dataclass


@dataclass
class Lease:
    max_steps: float
    max_duration: float
