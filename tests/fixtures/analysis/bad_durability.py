"""durability negative fixture: a raw write-mode open of state and a
bare os.replace (lines marked SEEDED)."""
import json
import os


def save_state(path, state):
    with open(path + ".tmp", "w") as f:  # SEEDED: raw write-mode open
        json.dump(state, f)
    os.replace(path + ".tmp", path)  # SEEDED: rename outside durable_io


def load_state(path):
    with open(path) as f:  # read-mode: not a finding
        return json.load(f)
