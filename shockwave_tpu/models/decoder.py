"""Decoder-only LM with an explicit KV cache for autoregressive serving.

The serving tier's replica workload (workloads/serving/serve.py) decodes
tokens one at a time; recomputing attention over the whole prefix every
step would make per-token cost quadratic in position. The standard fix —
cache each layer's projected K/V and attend the new token's query
against the cache — makes decode O(1) per token in recompute (cf. the
autoregressive-caching compiler line of work, PAPERS.md 2603.09555).

Built on the existing stack: the full-sequence path reuses the same
head/projection shapes as `models/transformer.py` and lowers to the
Pallas flash-attention kernel (`ops/flash_attention.py`) when shapes
allow, exactly like `MultiHeadAttention`; the decode path shares the
same parameters (flax setup-defined submodules) and attends against the
cache with masked einsum — a 1-token query has no flash-block shape.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .transformer import sinusoidal_positions


class CachedSelfAttention(nn.Module):
    """Causal self-attention whose parameters serve both the
    full-sequence (prefill / parity) path and the single-token cached
    decode path."""
    num_heads: int
    dim: int
    dtype: Any = jnp.float32
    use_flash: bool = False

    def setup(self):
        head_dim = self.dim // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype, name=name)
        self.query = dense("query")
        self.key = dense("key")
        self.value = dense("value")
        self.out = nn.DenseGeneral(self.dim, axis=(-2, -1),
                                   dtype=self.dtype, name="out")

    def __call__(self, x):
        """Full-sequence causal attention (flash-capable, same shape
        gate as transformer.MultiHeadAttention)."""
        q, k, v = self.query(x), self.key(x), self.value(x)
        t = q.shape[1]
        head_dim = self.dim // self.num_heads
        align = 16 if self.dtype == jnp.bfloat16 else 8
        blockable = t % 1024 == 0 if t > 1024 else t % align == 0
        if self.use_flash and blockable:
            from ..ops import flash_attention
            attended = flash_attention(q, k, v, causal=True)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(head_dim)
            mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
            scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
            weights = nn.softmax(
                scores.astype(jnp.float32)).astype(self.dtype)
            attended = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        return self.out(attended)

    def decode(self, x, k_cache, v_cache, pos):
        """One-token step: write this position's K/V into the cache and
        attend the query over every cached position <= pos.

        x: (B, 1, D); caches: (B, T, H, Dh); pos: scalar int32."""
        q = self.query(x)
        k_cache = jax.lax.dynamic_update_slice_in_dim(
            k_cache, self.key(x).astype(k_cache.dtype), pos, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(
            v_cache, self.value(x).astype(v_cache.dtype), pos, axis=1)
        head_dim = self.dim // self.num_heads
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache) / jnp.sqrt(head_dim)
        valid = (jnp.arange(k_cache.shape[1]) <= pos)[None, None, None, :]
        scores = jnp.where(valid, scores, jnp.finfo(jnp.float32).min)
        weights = nn.softmax(scores.astype(jnp.float32)).astype(self.dtype)
        attended = jnp.einsum("bhqk,bkhd->bqhd", weights, v_cache)
        return self.out(attended), k_cache, v_cache


class DecoderBlock(nn.Module):
    """Pre-LN block, same composition as transformer.TransformerLayer."""
    num_heads: int
    dim: int
    mlp_dim: int
    dtype: Any = jnp.float32
    use_flash: bool = False

    def setup(self):
        self.attn = CachedSelfAttention(self.num_heads, self.dim,
                                        self.dtype, self.use_flash,
                                        name="self_attn")
        self.norm1 = nn.LayerNorm(dtype=jnp.float32)
        self.norm2 = nn.LayerNorm(dtype=jnp.float32)
        self.mlp_in = nn.Dense(self.mlp_dim, dtype=self.dtype)
        self.mlp_out = nn.Dense(self.dim, dtype=self.dtype)

    def _mlp(self, x):
        return self.mlp_out(nn.gelu(self.mlp_in(x)))

    def __call__(self, x):
        x = x + self.attn(self.norm1(x))
        return x + self._mlp(self.norm2(x))

    def decode(self, x, k_cache, v_cache, pos):
        attended, k_cache, v_cache = self.attn.decode(
            self.norm1(x), k_cache, v_cache, pos)
        x = x + attended
        return x + self._mlp(self.norm2(x)), k_cache, v_cache


class DecoderLM(nn.Module):
    """Small decoder-only LM for token serving (sized for one chip; the
    serving workload scales by replica count, not model size)."""
    vocab_size: int = 256
    dim: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_dim: int = 256
    max_len: int = 128
    dtype: Any = jnp.float32
    use_flash: bool = False

    def setup(self):
        self.embed = nn.Embed(self.vocab_size, self.dim,
                              embedding_init=nn.initializers.normal(0.02),
                              name="embed")
        self.blocks = [DecoderBlock(self.num_heads, self.dim, self.mlp_dim,
                                    self.dtype, self.use_flash,
                                    name=f"block_{i}")
                       for i in range(self.num_layers)]
        self.final_norm = nn.LayerNorm(dtype=jnp.float32)

    def _positions(self):
        return jnp.asarray(sinusoidal_positions(self.max_len, self.dim))

    def _logits(self, x):
        # Tied output projection, like Seq2SeqTransformer.
        return jnp.einsum("bld,vd->blv", x.astype(jnp.float32),
                          self.embed.embedding.astype(jnp.float32))

    def __call__(self, tokens):
        """Full-sequence causal logits (prefill and the decode-parity
        oracle in tests)."""
        x = self.embed(tokens).astype(self.dtype)
        x = x + self._positions()[: tokens.shape[1]]
        for block in self.blocks:
            x = block(x)
        return self._logits(self.final_norm(x))

    def decode_step(self, token, caches, pos):
        """One autoregressive step. token: (B, 1) int32; caches: pytree
        from `init_cache`; pos: scalar position of `token`. Returns
        (logits (B, 1, V), updated caches)."""
        x = self.embed(token).astype(self.dtype)
        x = x + jax.lax.dynamic_slice_in_dim(self._positions(), pos, 1,
                                             axis=0)
        new_caches = []
        for block, (k_cache, v_cache) in zip(self.blocks, caches):
            x, k_cache, v_cache = block.decode(x, k_cache, v_cache, pos)
            new_caches.append((k_cache, v_cache))
        return self._logits(self.final_norm(x)), new_caches

    def init_cache(self, batch: int) -> Tuple:
        head_dim = self.dim // self.num_heads
        shape = (batch, self.max_len, self.num_heads, head_dim)
        return tuple((jnp.zeros(shape, self.dtype),
                      jnp.zeros(shape, self.dtype))
                     for _ in range(self.num_layers))


def greedy_decode(model: DecoderLM, params: Dict, prompt: jnp.ndarray,
                  num_tokens: int):
    """Greedy autoregressive generation: prefill the prompt through the
    cache token-by-token, then extend `num_tokens` — the serving
    replica's unit of work. Returns (B, num_tokens) generated ids.
    jit-friendly: fixed trip counts, carries only (token, caches, pos)."""
    batch, prompt_len = prompt.shape
    caches = model.init_cache(batch)

    def step(carry, token_in):
        caches, pos = carry
        logits, caches = model.apply(params, token_in, caches, pos,
                                     method=DecoderLM.decode_step)
        next_token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return (caches, pos + 1), next_token[:, None]

    carry = (caches, jnp.int32(0))
    token = prompt[:, :1]
    # Prefill: feed prompt tokens through the cached path.
    for i in range(prompt_len):
        carry, next_token = step(carry, prompt[:, i:i + 1])
    generated = []
    token = next_token
    for _ in range(num_tokens):
        generated.append(token)
        carry, token = step(carry, token)
    return jnp.concatenate(generated, axis=1)


__all__ = ["CachedSelfAttention", "DecoderBlock", "DecoderLM",
           "greedy_decode"]
