from . import journal
from .scheduler import Scheduler, SchedulerConfig

__all__ = ["Scheduler", "SchedulerConfig", "journal"]
