"""Dynamic Eisenberg-Gale scheduling MILP on scipy/HiGHS.

Plans a boolean job x round schedule over a future horizon maximizing
approximate Nash social welfare over per-job training *progress*, with a
makespan regularizer and finish-time-fairness (FTF) constraints
(reference: scheduler/shockwave.py:288-711). The reference encodes this
in cvxpy and solves with Gurobi; here the model is assembled as sparse
matrices for scipy.optimize.milp (HiGHS), with the same infeasibility
fallback chain: drop FTF constraints, boost utilities of rho-violating
jobs by ratio**lambda, re-solve, then re-rank rounds to front-load
high-priority jobs.

Model per job j (horizon R rounds, log-approximation bases B):
  x[j,r] in {0,1}   job scheduled in round r
  p[j] >= 0         planned progress in epochs
  w[j,b] >= 0       SOS2-ish cursor weights over the log bases
  z[j,b] in {0,1}   which (at most 2, adjacent) bases are active
  s[j] >= 0         remaining runtime after the plan

  p[j] * dur[j] <= round_duration * sum_r x[j,r]
  sum_b w[j,b] * base[b] = (progress[j] + p[j]) / epochs[j]
  sum_b w[j,b] = 1;  w[j,b] <= z[j,b];  sum_b z[j,b] <= 2
  z[j,l] + z[j,r] <= 1 for |l-r| >= 2           (adjacency)
  s[j] >= D[j] - p[j] * dur[j]                  (D = Dirichlet remaining)
  s[j] <= (rhomax * runavg[j] - T_next) * share (FTF; first attempt only)
  sum_j nworkers[j] * x[j,r] <= ngpus           (capacity per round)

  maximize sum_j prio[j] * (sum_b w[j,b]*log(base[b])) / (njobs*R) - k*max_j s[j]
"""
from __future__ import annotations

import logging
import math
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
from scipy import sparse
from scipy.optimize import Bounds, LinearConstraint, milp

logger = logging.getLogger("shockwave_tpu.shockwave")


@dataclass
class MilpOptions:
    rel_gap: float = 1e-3
    timeout: float = 15.0
    rhomax: float = 1.0
    k: float = 1e-3
    lam: float = 12.0
    logapx_bases: Sequence[float] = (0.0, 0.2, 0.4, 0.6, 0.8, 1.0)
    logapx_origin: float = 1e-6
    # Per-solve wall budget bound, in round-durations. 0.5 keeps a hard
    # instance from stalling a PHYSICAL round loop; pure simulation can
    # raise it (config key "solver_budget_cap_rounds") — at 900 jobs the
    # single-threaded half-round budget is 6x less solver compute than
    # the reference's 15 s x 24 Gurobi threads and measurably starves
    # incumbent quality (gap 6.8e-2, no-incumbent greedy fallbacks).
    budget_cap_rounds: float = 0.5


@dataclass
class SolveStats:
    """Per-plan_schedule solve-quality telemetry (the reference bounds
    its solver with MIPGap/TimeLimit, configurations/tacc_32gpus.json,
    but never records what the solver actually achieved; scale runs
    need that to prove the fallback chain stays cold).

    `path` is the outcome of the fallback chain:
      ftf            — first attempt (with FTF constraints) solved
      relaxed        — FTF infeasible/timed out; relaxed solve succeeded
      relaxed_retry  — relaxed solve needed the long-budget retry
      greedy         — every MILP failed; greedy fallback schedule
    """
    round_index: int
    njobs: int
    path: str
    wall_s: float
    status: Optional[int] = None       # scipy milp status of final solve
    mip_gap: Optional[float] = None    # achieved relative gap, if exposed
    ftf_infeasible: bool = False       # FTF caps provably infeasible
    # Solver EXCEPTION (not mere infeasibility) swallowed by the guard
    # around _solve: the round loop degraded to the next fallback arm
    # instead of dying. "<ExcType>: <msg>" of the last raise, else None.
    error: Optional[str] = None
    # Assembly/solve wall split: time spent building the sparse model
    # (structure splice + COO->CSR), included in wall_s. Proves where
    # the wall went (the scale pickles previously could not distinguish
    # a slow solver from a slow model build).
    assembly_s: float = 0.0
    # True when this solve ran on the background planner thread
    # (physical pipelined planning) instead of the round-loop critical
    # path.
    pipelined: bool = False


def finish_time_momentumed_average(series, round_index, momentum=0.9) -> float:
    """Running average of finish-time estimates weighted by how long each
    estimate was current, blended with the latest estimate
    (reference: shockwave.py:480-501)."""
    assert len(series) > 0
    rounds = [r for r, _ in series] + [round_index]
    windows = np.diff(rounds)
    if windows.max(initial=0) == 0:
        probs = [1.0]
    else:
        probs = (windows / windows.sum()).tolist()
    values = [v for _, v in series]
    running = sum(p * v for p, v in zip(probs, values))
    return momentum * running + (1.0 - momentum) * values[-1]


def finish_time_momentumed_averages(series_list, round_index,
                                    momentum=0.9) -> List[float]:
    """Vectorized `finish_time_momentumed_average` over all jobs.

    plan_schedule calls the scalar version once per job per solve; at
    900 jobs that rebuilds ~900 tiny numpy arrays per re-solve. Series
    grow in lockstep (every estimate refresh appends to every active
    job), so batching by length turns the whole pass into a handful of
    2D diff/divide/accumulate calls.

    Bit-identical to the scalar version by construction: elementwise
    ops reassociate nothing, and the weighted sum uses
    ``np.add.accumulate`` (strictly sequential prefix sums — the same
    left-to-right association as the scalar ``sum()``), never
    ``np.sum`` (pairwise). Returns python floats so downstream
    ``ratio ** power`` overflow behavior (OverflowError, caught in
    _relaxation_priorities) is preserved — numpy scalars would yield
    inf silently.
    """
    out: List[float] = [0.0] * len(series_list)
    by_len: dict = {}
    for i, series in enumerate(series_list):
        assert len(series) > 0
        by_len.setdefault(len(series), []).append(i)
    for length, idxs in by_len.items():
        arr = np.asarray([series_list[i] for i in idxs],
                         dtype=np.float64)               # (G, L, 2)
        values = arr[:, :, 1]
        rounds = np.concatenate(
            [arr[:, :, 0],
             np.full((len(idxs), 1), round_index, dtype=np.float64)],
            axis=1)
        windows = np.diff(rounds, axis=1)                # (G, L)
        totals = windows.sum(axis=1)
        degenerate = totals == 0
        safe_totals = np.where(degenerate, 1.0, totals)
        probs = windows / safe_totals[:, None]
        running = np.add.accumulate(probs * values, axis=1)[:, -1]
        # All-zero windows: the scalar version collapses probs to [1.0]
        # and the weighted sum reduces to the first value.
        running = np.where(degenerate, values[:, 0], running)
        blended = momentum * running + (1.0 - momentum) * values[:, -1]
        for g, i in enumerate(idxs):
            out[i] = float(blended[g])
    return out


class _Layout:
    """Variable indexing for the MILP."""

    def __init__(self, njobs: int, nrounds: int, nbases: int):
        self.R, self.B = nrounds, nbases
        self.stride = nrounds + 1 + 2 * nbases + 1
        self.njobs = njobs
        self.n = njobs * self.stride + 1  # + global t

    def x(self, j, r): return j * self.stride + r
    def p(self, j): return j * self.stride + self.R
    def w(self, j, b): return j * self.stride + self.R + 1 + b
    def z(self, j, b): return j * self.stride + self.R + 1 + self.B + b
    def s(self, j): return j * self.stride + self.R + 1 + 2 * self.B
    @property
    def t(self): return self.n - 1


class _ShapeStructure:
    """Structurally-static assembly pattern for one (njobs, R, B) shape.

    Every COO row/col index of the EG model, the constant coefficient
    values, the b-vector constants, integrality and variable bounds
    depend only on the shape — not on the per-solve data — so they are
    built once here (vectorized) and cached (`_structure_for`). A solve
    then only splices the data that changes (nworkers, durations,
    dirichlet, progress, ftf caps, priorities) into preallocated slots:
    see _InstanceAssembler.

    Two row numberings coexist: the FTF variant appends one extra
    inequality row per job *inside* that job's block, shifting every
    later row, so both variants' row arrays are materialized.
    """

    def __init__(self, njobs: int, R: int, B: int):
        self.njobs, self.R, self.B = njobs, R, B
        stride = R + 1 + 2 * B + 1
        self.stride = stride
        self.n = njobs * stride + 1
        self.t = self.n - 1
        nadj = (B - 2) * (B - 1) // 2 if B > 2 else 0
        self.nadj = nadj

        j = np.arange(njobs, dtype=np.int64)
        b = np.arange(B, dtype=np.int64)
        r = np.arange(R, dtype=np.int64)
        jcol = j * stride
        self.x_cols = jcol[:, None] + r[None, :]          # (njobs, R)
        self.p_cols = jcol + R
        self.w_cols = jcol[:, None] + (R + 1) + b[None, :]  # (njobs, B)
        self.z_cols = self.w_cols + B
        self.s_cols = jcol + R + 1 + 2 * B

        # Adjacency pair offsets (lo, hi) with hi >= lo + 2, lo-major —
        # the loop order of the reference assembler.
        lo, hi = [], []
        for lo_i in range(B - 2):
            for hi_i in range(lo_i + 2, B):
                lo.append(lo_i)
                hi.append(hi_i)
        lo_a = np.asarray(lo, dtype=np.int64)
        hi_a = np.asarray(hi, dtype=np.int64)

        # ---- common A_ub column pattern (concatenation order fixed) --
        # cap:    R rows x njobs entries      (vals <- nworkers)
        # run-p:  1 row/job, p entry          (vals <- durations)
        # run-x:  same rows, R entries        (vals <- -round_duration)
        # wz-w /  B rows/job, w then z entry  (vals 1 / -1)
        # wz-z
        # sumz:   1 row/job, B entries        (vals 1)
        # adj-lo/ nadj rows/job, two entries  (vals 1)
        # adj-hi
        # rem-s:  1 row/job, s entry          (vals -1)
        # rem-p:  same rows, p entry          (vals <- -durations)
        # mk-s:   1 row/job, s entry          (vals 1)
        # mk-t:   same rows, t entry          (vals -1)
        cols = [
            np.tile(jcol, R) + np.repeat(r, njobs),       # cap
            self.p_cols,                                  # run-p
            self.x_cols.ravel(),                          # run-x
            self.w_cols.ravel(),                          # wz-w
            self.z_cols.ravel(),                          # wz-z
            self.z_cols.ravel(),                          # sumz
            (jcol[:, None] + (R + 1 + B) + lo_a[None, :]).ravel(),
            (jcol[:, None] + (R + 1 + B) + hi_a[None, :]).ravel(),
            self.s_cols,                                  # rem-s
            self.p_cols,                                  # rem-p
            self.s_cols,                                  # mk-s
            np.full(njobs, self.t, dtype=np.int64),       # mk-t
        ]
        sizes = [c.size for c in cols]
        self.cols_common = np.concatenate(cols)
        offsets = np.cumsum([0] + sizes)
        sl = [slice(offsets[i], offsets[i + 1]) for i in range(len(sizes))]
        (self.sl_cap, self.sl_runp, self.sl_runx, self.sl_wzw,
         self.sl_wzz, self.sl_sumz, self.sl_adjlo, self.sl_adjhi,
         self.sl_rems, self.sl_remp, self.sl_mks, self.sl_mkt) = sl

        # Constant coefficients pre-filled; per-solve slots overwritten
        # by the assembler (cap / run-p / run-x / rem-p).
        tmpl = np.empty(self.cols_common.size, dtype=np.float64)
        tmpl[self.sl_wzw] = 1.0
        tmpl[self.sl_wzz] = -1.0
        tmpl[self.sl_sumz] = 1.0
        tmpl[self.sl_adjlo] = 1.0
        tmpl[self.sl_adjhi] = 1.0
        tmpl[self.sl_rems] = -1.0
        tmpl[self.sl_mks] = 1.0
        tmpl[self.sl_mkt] = -1.0
        self.vals_template = tmpl

        # ---- row numbering for both variants ------------------------
        def rows_for(block):
            base = R + j * block
            parts = [
                np.repeat(r, njobs),                      # cap rows
                base,                                     # run-p
                np.repeat(base, R),                       # run-x
                (base[:, None] + 1 + b[None, :]).ravel(),  # wz-w
                (base[:, None] + 1 + b[None, :]).ravel(),  # wz-z
                np.repeat(base + 1 + B, B),               # sumz
                (base[:, None] + B + 2
                 + np.arange(nadj, dtype=np.int64)[None, :]).ravel(),
                (base[:, None] + B + 2
                 + np.arange(nadj, dtype=np.int64)[None, :]).ravel(),
                base + B + 2 + nadj,                      # rem-s
                base + B + 2 + nadj,                      # rem-p
                base + B + 3 + nadj,                      # mk-s
                base + B + 3 + nadj,                      # mk-t
            ]
            return np.concatenate(parts), base

        block_relaxed = B + nadj + 4
        block_ftf = block_relaxed + 1
        self.rows_relaxed, base_r = rows_for(block_relaxed)
        rows_ftf_common, base_f = rows_for(block_ftf)
        self.ftf_rows = base_f + B + 4 + nadj
        self.rows_ftf = np.concatenate([rows_ftf_common, self.ftf_rows])
        self.cols_ftf = np.concatenate([self.cols_common, self.s_cols])
        self.nrows_relaxed = R + njobs * block_relaxed
        self.nrows_ftf = R + njobs * block_ftf

        # b_ub templates (constants filled; ngpus / dirichlet / ftf caps
        # spliced per solve). Row index arrays for the spliced slots.
        def b_template(base, nrows):
            tmpl = np.zeros(nrows, dtype=np.float64)
            tmpl[base + 1 + B] = 2.0                      # sumz
            adj_rows = (base[:, None] + B + 2
                        + np.arange(nadj, dtype=np.int64)[None, :]).ravel()
            tmpl[adj_rows] = 1.0
            return tmpl, base + B + 2 + nadj              # rem rows

        self.b_template_relaxed, self.rem_rows_relaxed = b_template(
            base_r, self.nrows_relaxed)
        self.b_template_ftf, self.rem_rows_ftf = b_template(
            base_f, self.nrows_ftf)

        # ---- equality pattern ----------------------------------------
        # Per job: row 2j (log cursor), row 2j+1 (sum w = 1).
        self.eq_rows = np.concatenate([
            np.repeat(2 * j, B),                          # cursor-w
            2 * j,                                        # cursor-p
            np.repeat(2 * j + 1, B),                      # sumw
        ])
        self.eq_cols = np.concatenate([
            self.w_cols.ravel(), self.p_cols, self.w_cols.ravel()])
        self.sl_eq_bases = slice(0, njobs * B)
        self.sl_eq_p = slice(njobs * B, njobs * B + njobs)
        eq_tmpl = np.empty(self.eq_cols.size, dtype=np.float64)
        eq_tmpl[njobs * B + njobs:] = 1.0                 # sumw entries
        self.vals_eq_template = eq_tmpl
        self.nrows_eq = 2 * njobs

        # ---- integrality / bounds (pure shape) -----------------------
        integrality = np.zeros(self.n)
        ub = np.full(self.n, np.inf)
        integrality[self.x_cols.ravel()] = 1
        integrality[self.z_cols.ravel()] = 1
        ub[self.x_cols.ravel()] = 1
        ub[self.z_cols.ravel()] = 1
        ub[self.w_cols.ravel()] = 1
        self.integrality = integrality
        self.ub = ub


_STRUCTURE_CACHE: "OrderedDict[tuple, _ShapeStructure]" = OrderedDict()
_STRUCTURE_CACHE_MAX = 8
_STRUCTURE_LOCK = threading.Lock()


def _structure_for(njobs: int, R: int, B: int) -> _ShapeStructure:
    """LRU-cached shape structure. njobs shrinks as the trace drains, so
    a handful of recent shapes covers the REOPT_ROUNDS solve cadence."""
    key = (njobs, R, B)
    with _STRUCTURE_LOCK:
        cached = _STRUCTURE_CACHE.get(key)
        if cached is not None:
            _STRUCTURE_CACHE.move_to_end(key)
            return cached
    built = _ShapeStructure(njobs, R, B)
    with _STRUCTURE_LOCK:
        _STRUCTURE_CACHE[key] = built
        _STRUCTURE_CACHE.move_to_end(key)
        while len(_STRUCTURE_CACHE) > _STRUCTURE_CACHE_MAX:
            _STRUCTURE_CACHE.popitem(last=False)
    return built


class _InstanceAssembler:
    """Per-solve model assembly over the cached shape structure.

    One assembler is built per plan_schedule call and SHARED between
    the FTF attempt and the relax fallback: the equality block and the
    common inequality values are spliced once; each variant then only
    differs by its row numbering (cached structure), its b vector, and
    the objective (priorities). Produces matrices byte-identical to the
    historical pure-python loop assembler (golden-equivalence suite in
    tests/test_milp_assembly.py keeps the loop oracle).
    """

    def __init__(self, S: _ShapeStructure, bases, base_logs, nworkers,
                 durations, dirichlet, progress, epochs, ftf_caps,
                 round_duration: float, ngpus: int, k: float):
        self.S = S
        self.base_logs = np.asarray(base_logs, dtype=np.float64)
        self.ngpus = ngpus
        self.k = k
        self.ftf_caps = np.asarray(ftf_caps, dtype=np.float64)
        self.ftf_infeasible = bool(np.any(self.ftf_caps < 0))
        durations_f = np.asarray(durations, dtype=np.float64)
        self.dirichlet = np.asarray(dirichlet, dtype=np.float64)

        vals = S.vals_template.copy()
        vals[S.sl_cap] = np.tile(
            np.asarray(nworkers, dtype=np.float64), S.R)
        vals[S.sl_runp] = durations_f
        vals[S.sl_runx] = -round_duration
        vals[S.sl_remp] = -durations_f
        self._vals_common = vals

        vals_eq = S.vals_eq_template.copy()
        vals_eq[S.sl_eq_bases] = np.tile(
            np.asarray(bases, dtype=np.float64), S.njobs)
        epochs_f = np.asarray(epochs, dtype=np.float64)
        vals_eq[S.sl_eq_p] = -1.0 / epochs_f
        self.A_eq = sparse.coo_matrix(
            (vals_eq, (S.eq_rows, S.eq_cols)),
            shape=(S.nrows_eq, S.n)).tocsr()
        self.b_eq = np.zeros(S.nrows_eq)
        self.b_eq[0::2] = np.asarray(progress, dtype=np.float64) / epochs_f
        self.b_eq[1::2] = 1.0

        self._A_ub = {}  # variant -> CSR, built lazily, reused per arm
        self._b_ub = {}

    def _inequalities(self, with_ftf: bool):
        S = self.S
        cached = self._A_ub.get(with_ftf)
        if cached is None:
            if with_ftf:
                vals = np.concatenate(
                    [self._vals_common, np.ones(S.njobs)])
                cached = sparse.coo_matrix(
                    (vals, (S.rows_ftf, S.cols_ftf)),
                    shape=(S.nrows_ftf, S.n)).tocsr()
                b = S.b_template_ftf.copy()
                b[:S.R] = self.ngpus
                b[S.rem_rows_ftf] = -self.dirichlet
                b[S.ftf_rows] = self.ftf_caps
            else:
                cached = sparse.coo_matrix(
                    (self._vals_common, (S.rows_relaxed, S.cols_common)),
                    shape=(S.nrows_relaxed, S.n)).tocsr()
                b = S.b_template_relaxed.copy()
                b[:S.R] = self.ngpus
                b[S.rem_rows_relaxed] = -self.dirichlet
            self._A_ub[with_ftf] = cached
            self._b_ub[with_ftf] = b
        return cached, self._b_ub[with_ftf]

    def model(self, priorities, with_ftf: bool):
        """(c, A_ub, b_ub, A_eq, b_eq, integrality, ub) for one arm, or
        None when with_ftf and the caps are provably infeasible."""
        if with_ftf and self.ftf_infeasible:
            return None
        S = self.S
        A_ub, b_ub = self._inequalities(with_ftf)
        c = np.zeros(S.n)
        c[S.w_cols.ravel()] = (
            (-np.asarray(priorities, dtype=np.float64))[:, None]
            * self.base_logs[None, :] / (S.njobs * S.R)).ravel()
        c[S.t] = self.k
        return (c, A_ub, b_ub, self.A_eq, self.b_eq,
                S.integrality.copy(), S.ub.copy())


class _FailedSolve:
    """Result shim for a solver that RAISED (scipy/HiGHS internal error,
    numerical blow-up, ...): looks like a failed `milp` result so the
    existing fallback chain (relax -> greedy) handles it, and carries
    the exception text into SolveStats.error."""

    x = None
    status = None
    mip_gap = None

    def __init__(self, error: str):
        self.error = error


def _solve(c, A_ub, b_ub, A_eq, b_eq, integrality, ub, opts: MilpOptions,
           timeout_scale: float = 1.0):
    constraints = []
    if len(b_ub):
        constraints.append(LinearConstraint(A_ub, -np.inf, b_ub))
    if len(b_eq):
        constraints.append(LinearConstraint(A_eq, b_eq, b_eq))
    try:
        res = milp(
            c, constraints=constraints, integrality=integrality,
            bounds=Bounds(np.zeros_like(ub), ub),
            options={"time_limit": opts.timeout * timeout_scale,
                     "mip_rel_gap": opts.rel_gap, "presolve": True},
        )
    except Exception as e:  # noqa: BLE001 - a solver crash must not kill
        # the round loop: degrade through the fallback chain instead.
        logger.warning("MILP solver raised %s: %s; treating as failed "
                       "solve", type(e).__name__, e)
        return _FailedSolve(f"{type(e).__name__}: {e}")
    return res


def plan_schedule(jobs, round_index: int, future_nrounds: int,
                  round_duration: float, ngpus: int, share_series: List[list],
                  opts: MilpOptions,
                  stats_out: Optional[list] = None,
                  pipelined: bool = False) -> np.ndarray:
    """Returns a boolean (njobs x future_nrounds) schedule matrix.

    With `stats_out`, appends one SolveStats record describing which
    arm of the fallback chain produced the schedule and the solver's
    achieved quality (status / MIP gap / wall time, with the
    assembly/solve split). `pipelined` is caller-provided provenance:
    True when this call runs on the background planner thread."""
    import time as _time
    # Solve wall time is telemetry riding a journaled SolveStats record:
    # replay reads the journaled outcome, never re-times the solve.
    _t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    _assembly = [0.0]

    def _record(path, res=None, ftf_infeasible=False):
        if stats_out is not None:
            gap = getattr(res, "mip_gap", None) if res is not None else None
            stats_out.append(SolveStats(
                round_index=round_index, njobs=len(jobs), path=path,
                wall_s=round(_time.monotonic() - _t0, 3),  # swtpu-check: ignore[determinism]
                status=getattr(res, "status", None) if res is not None
                else None,
                mip_gap=None if gap is None else float(gap),
                ftf_infeasible=ftf_infeasible,
                error=getattr(res, "error", None) if res is not None
                else None,
                assembly_s=round(_assembly[0], 4),
                pipelined=pipelined))
    njobs = len(jobs)
    bases = list(opts.logapx_bases)
    assert bases[0] == 0.0
    base_logs = [math.log(opts.logapx_origin)] + [math.log(b) for b in bases[1:]]
    L = _Layout(njobs, future_nrounds, len(bases))

    nworkers = [job.nworkers for job in jobs]
    durations = [job.interpolated_epoch_duration() for job in jobs]
    dirichlet = [job.dirichlet_posterior_remaining_runtime() for job in jobs]
    progress = [job.epoch_progress for job in jobs]
    epochs = [job.epochs for job in jobs]

    future_share = min(1.0, ngpus / njobs)
    next_sched_time = round_duration * (round_index + future_nrounds)
    runavg = finish_time_momentumed_averages(share_series, round_index)
    ftf_caps = [(opts.rhomax * runavg[j] - next_sched_time) * future_share
                for j in range(njobs)]

    # Vectorized incremental assembly: structure cached per shape, one
    # shared per-solve assembler across both fallback arms (the
    # historical pure-python loop assembler rebuilt the whole COO model
    # from scratch per arm — O(njobs * R * B^2) list appends; the loop
    # oracle survives in tests/test_milp_assembly.py as the
    # golden-equivalence reference).
    _a0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    assembler = _InstanceAssembler(
        _structure_for(njobs, future_nrounds, len(bases)),
        bases, base_logs, nworkers, durations, dirichlet, progress,
        epochs, ftf_caps, round_duration, ngpus, opts.k)
    _assembly[0] += _time.monotonic() - _a0  # swtpu-check: ignore[determinism]

    def assemble(priorities, with_ftf: bool):
        _a0 = _time.monotonic()  # swtpu-check: ignore[determinism]
        model = assembler.model(priorities, with_ftf)
        _assembly[0] += _time.monotonic() - _a0  # swtpu-check: ignore[determinism]
        return model

    # The reference gives Gurobi a flat 15 s on 24 threads
    # (configurations/*.json); single-threaded HiGHS needs the budget to
    # grow with the boolean count or large instances (hundreds of jobs)
    # time out with no incumbent at all. Canonical-scale problems
    # (<= 120 jobs) keep the reference budget exactly. Budgets stay
    # bounded by budget_cap_rounds round-durations per solve (2x that
    # for the one no-incumbent retry); at the 0.5 default — which
    # physical mode enforces (sched/scheduler.py clamps the config) — a
    # hard instance can never stall the round loop beyond half a round
    # per solve / one full round for the retry.
    timeout_scale = max(1.0, njobs / 120.0)
    cap = round_duration * opts.budget_cap_rounds
    solve_budget = min(opts.timeout * timeout_scale, cap)
    retry_budget = min(4.0 * solve_budget, 2.0 * cap)
    scale = solve_budget / opts.timeout

    # -- first attempt: with FTF constraints ------------------------------
    ones = [1.0] * njobs
    model = assemble(ones, with_ftf=True)
    res = None
    if model is not None:
        res = _solve(*model, opts, scale)
    if model is not None and res.x is not None and res.status in (0, 1):
        x = _extract(res.x, L, njobs, future_nrounds)
        _record("ftf", res)
        return x

    # -- fallback: relax FTF, boost violating jobs' utilities -------------
    if res is not None and getattr(res, "error", None):
        logger.info("FTF solve raised (%s) at round %d; relaxing",
                    res.error, round_index)
    elif res is not None and res.x is None and res.status == 1:
        logger.info("FTF solve timed out with no incumbent at round %d; "
                    "relaxing", round_index)
    else:
        logger.info("FTF constraints infeasible at round %d; relaxing",
                    round_index)
    ftf_infeasible = model is None
    priorities = _relaxation_priorities(
        jobs, dirichlet, runavg, round_index, round_duration, future_share,
        opts.rhomax, opts.lam)
    model = assemble(priorities, with_ftf=False)
    res = _solve(*model, opts, scale)
    retried = False
    if res.x is None and res.status == 1:
        # Timed out before finding any incumbent: one longer attempt is
        # much better than degrading to the greedy schedule.
        logger.info("relaxed MILP hit its time limit; retrying at %.0fs",
                    retry_budget)
        res = _solve(*model, opts, retry_budget / opts.timeout)
        retried = True
    if res.x is None:
        logger.warning("relaxed MILP failed (%s); greedy fallback", res.status)
        _record("greedy", res, ftf_infeasible)
        return _greedy_fallback(jobs, future_nrounds, ngpus, dirichlet)
    x = _extract(res.x, L, njobs, future_nrounds)
    _record("relaxed_retry" if retried else "relaxed", res, ftf_infeasible)
    return _rank_in_schedule(x, priorities, nworkers, ngpus, opts,
                             time_limit=solve_budget)


def _extract(xvec, L, njobs, nrounds) -> np.ndarray:
    # np.rint rounds half-to-even exactly like the historical per-entry
    # python round(); one gather instead of njobs*R indexing calls.
    idx = (np.arange(njobs) * L.stride)[:, None] + np.arange(nrounds)
    return np.rint(np.asarray(xvec)[idx]) == 1


def _relaxation_priorities(jobs, dirichlet, runavg, round_index,
                           round_duration, future_share, rhomax, lam):
    """Priority = projected-rho**lambda for jobs violating rhomax
    (reference: shockwave.py:830-911)."""
    PRIORITY_M = 1e2
    priorities = []
    round_time = round_duration * round_index
    for j, job in enumerate(jobs):
        job.calibrate_profiled_epoch_duration()
        remaining = dirichlet[j]
        projected_finish = round_time + remaining / future_share
        # Guarded divide: a degenerate zero fair-share finish average
        # (sub-epoch jobs, metadata.py) must not crash the solve. No
        # cap: the pinned canonical replay ranks by astronomically
        # large priorities for near-done jobs, and capping would
        # reorder those ties.
        ratio = projected_finish / max(runavg[j], 1e-6)
        if ratio > rhomax:
            power = PRIORITY_M if remaining < round_duration else lam
            try:
                priority = ratio ** power
            except OverflowError:
                # Degenerate runavg (sub-epoch jobs) can push the ratio
                # past float range at power 100.
                priority = 1e300
            priorities.append(priority)
        else:
            priorities.append(1.0)
    # Only RELATIVE priorities matter — they are NSW objective weights
    # (scale-invariant trade-offs) and rank keys — but their absolute
    # magnitude reaches HiGHS as objective coefficients, and ratio**100
    # boosts (up to the 1e300 overflow guard) make HiGHS return
    # "model_status Unknown" instantly, silently degrading every such
    # re-solve to the greedy fallback schedule (found by the round-5
    # solve telemetry: 12/16 solves on the 12-job fidelity trace).
    # Normalizing the maximum to 1e6 preserves the exact ranking and
    # relative weighting while keeping coefficients in HiGHS's
    # comfortable range.
    top = max(priorities)
    if top > 1e6:
        scale = 1e6 / top
        priorities = [p * scale for p in priorities]
    return priorities


def _rank_model(x: np.ndarray, priorities, nworkers, ngpus):
    """Vectorized assembly of the rank-in-schedule model:
    (c, A_ub, b_ub, A_eq, b_eq). Same matrices the historical loop
    assembler produced (oracle kept in tests/test_milp_assembly.py)."""
    njobs, nrounds = x.shape
    counts = x.sum(axis=1)
    n = njobs * nrounds
    j = np.arange(njobs, dtype=np.int64)
    r = np.arange(nrounds, dtype=np.int64)

    rows_ub = np.repeat(r, njobs)
    cols_ub = np.tile(j * nrounds, nrounds) + rows_ub
    vals_ub = np.tile(np.asarray(nworkers, dtype=np.float64), nrounds)
    b_ub = np.full(nrounds, ngpus, dtype=np.float64)

    rows_eq = np.repeat(j, nrounds)
    cols_eq = np.arange(n, dtype=np.int64)
    vals_eq = np.ones(n)
    b_eq = counts.astype(np.float64)

    counts_f = counts.astype(np.float64)
    c = (np.asarray(priorities, dtype=np.float64)[:, None]
         * r.astype(np.float64)[None, :])
    np.divide(c, counts_f[:, None], out=c, where=counts_f[:, None] > 0)
    c[counts == 0, :] = 0.0

    A_ub = sparse.coo_matrix((vals_ub, (rows_ub, cols_ub)),
                             shape=(nrounds, n)).tocsr()
    A_eq = sparse.coo_matrix((vals_eq, (rows_eq, cols_eq)),
                             shape=(njobs, n)).tocsr()
    return c.ravel(), A_ub, b_ub, A_eq, b_eq


def _rank_in_schedule(x: np.ndarray, priorities, nworkers, ngpus,
                      opts: MilpOptions,
                      time_limit: Optional[float] = None) -> np.ndarray:
    """Second MILP: keep each job's number of scheduled rounds but permute
    rounds so high-priority jobs run earlier (reference: shockwave.py:714-793).
    `time_limit` inherits the (scaled, round-bounded) budget of the main
    solve — this model has the same njobs x nrounds boolean count."""
    njobs, nrounds = x.shape
    counts = x.sum(axis=1)
    if not np.any(counts > 0):
        return x

    n = njobs * nrounds
    c, A_ub, b_ub, A_eq, b_eq = _rank_model(x, priorities, nworkers, ngpus)

    try:
        res = milp(
            c,
            constraints=[
                LinearConstraint(A_ub, -np.inf, b_ub),
                LinearConstraint(A_eq, b_eq, b_eq),
            ],
            integrality=np.ones(n),
            bounds=Bounds(np.zeros(n), np.ones(n)),
            options={"time_limit": time_limit or opts.timeout,
                     "mip_rel_gap": opts.rel_gap, "presolve": True},
        )
    except Exception as e:  # noqa: BLE001 - ranking is an optimization;
        # the unranked schedule is valid, so never die for it.
        logger.warning("rank-in-schedule MILP raised %s: %s; keeping "
                       "unranked schedule", type(e).__name__, e)
        return x
    if res.x is None:
        logger.warning("rank-in-schedule MILP failed (%s); "
                       "keeping unranked schedule", res.status)
        return x
    return np.round(res.x.reshape((njobs, nrounds))).astype(bool)


def _greedy_fallback(jobs, nrounds, ngpus, dirichlet) -> np.ndarray:
    """Last-resort heuristic: longest remaining runtime first, every round."""
    njobs = len(jobs)
    order = sorted(range(njobs), key=lambda j: -dirichlet[j])
    x = np.zeros((njobs, nrounds), dtype=bool)
    for r in range(nrounds):
        free = ngpus
        for j in order:
            if jobs[j].nworkers <= free:
                x[j, r] = True
                free -= jobs[j].nworkers
            if free <= 0:
                break
    return x
