"""Workload layer tests: Trainer scaffold, adaptation monitors, checkpoint
round-trips, and (slow) full workload entry-point smokes."""
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.models.train_common import (AccordionMonitor, GNSMonitor,
                                               Trainer, load_checkpoint,
                                               save_checkpoint)

REPO = os.path.join(os.path.dirname(__file__), "..")
WORKLOADS = os.path.join(REPO, "shockwave_tpu", "workloads")


class FakeArgs:
    num_steps = 12
    local_rank = 0
    checkpoint_dir = None
    enable_lease_iterator = False
    throughput_estimation_interval = 100
    coordinator = None
    num_processes = None
    process_id = None
    synthetic_data = True


class TinyData:
    def __init__(self, n=4):
        rng = np.random.RandomState(0)
        self._batches = [(rng.rand(8, 4).astype(np.float32),
                          rng.rand(8, 1).astype(np.float32))
                         for _ in range(n)]

    def __len__(self):
        return len(self._batches)

    def __iter__(self):
        return iter(self._batches)


def tiny_trainer(tmp_path, mode="static", num_steps=12):
    args = FakeArgs()
    args.checkpoint_dir = str(tmp_path)
    args.num_steps = num_steps
    params = {"w": jnp.zeros((4, 1))}

    def loss_fn(p, state, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2), {}

    return Trainer(args, loss_fn, {"params": params}, TinyData(), mode=mode,
                   initial_bs=8, max_bs=32, learning_rate=0.1)


class TestTrainer:
    def test_runs_and_checkpoints(self, tmp_path):
        trainer = tiny_trainer(tmp_path)
        steps = trainer.run()
        assert steps == 12
        assert int(trainer.state["step"]) == 12
        # Resume from checkpoint: a fresh trainer continues at step 12.
        trainer2 = tiny_trainer(tmp_path, num_steps=16)
        steps2 = trainer2.run()
        assert steps2 == 4
        assert int(trainer2.state["step"]) == 16

    def test_loss_decreases(self, tmp_path):
        trainer = tiny_trainer(tmp_path, num_steps=30)
        state0 = trainer.state
        x, y = next(iter(TinyData()))
        loss_before = float(jnp.mean((x @ np.asarray(state0["params"]["w"]) - y) ** 2))
        trainer.run()
        w = np.asarray(trainer.state["params"]["w"])
        loss_after = float(jnp.mean((x @ w - y) ** 2))
        assert loss_after < loss_before

    def test_gns_mode_tracks_small_norms(self, tmp_path):
        trainer = tiny_trainer(tmp_path, mode="gns")
        state, metrics = trainer.train_step(trainer.state, *next(iter(TinyData())))
        assert "grad_norm_sq_small" in metrics


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        state = {"params": {"w": jnp.arange(4.0)}, "step": jnp.int32(7)}
        path = str(tmp_path / "ckpt" / "model.ckpt")
        save_checkpoint(path, state)
        restored = load_checkpoint(path, jax.device_get(state))
        assert int(restored["step"]) == 7
        np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                      np.arange(4.0))

    def test_missing_returns_none(self, tmp_path):
        assert load_checkpoint(str(tmp_path / "nope.ckpt"), {}) is None

    def _save_two(self, tmp_path):
        """Two generations: current says step 9, previous says step 7."""
        path = str(tmp_path / "ckpt" / "model.ckpt")
        template = {"params": {"w": jnp.arange(4.0)}, "step": jnp.int32(0)}
        save_checkpoint(path, {"params": {"w": jnp.arange(4.0)},
                               "step": jnp.int32(7)})
        save_checkpoint(path, {"params": {"w": jnp.arange(4.0)},
                               "step": jnp.int32(9)})
        return path, jax.device_get(template)

    @pytest.mark.recovery
    def test_previous_checkpoint_retained(self, tmp_path):
        path, template = self._save_two(tmp_path)
        assert os.path.exists(path + ".prev")
        assert int(load_checkpoint(path, template)["step"]) == 9

    @pytest.mark.recovery
    def test_corrupt_current_falls_back_to_previous(self, tmp_path):
        path, template = self._save_two(tmp_path)
        with open(path, "r+b") as f:
            f.seek(5)
            f.write(b"\xde\xad\xbe\xef")  # CRC now fails
        restored = load_checkpoint(path, template)
        assert restored is not None and int(restored["step"]) == 7

    @pytest.mark.recovery
    def test_truncated_current_falls_back(self, tmp_path):
        """A preemption mid-write tears the file: footer missing."""
        path, template = self._save_two(tmp_path)
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        restored = load_checkpoint(path, template)
        # Either the torn payload fails msgpack decode or the footer is
        # gone; both roads lead to the previous checkpoint.
        assert restored is not None and int(restored["step"]) == 7

    @pytest.mark.recovery
    def test_both_corrupt_fresh_start_not_crash(self, tmp_path):
        path, template = self._save_two(tmp_path)
        for p in (path, path + ".prev"):
            with open(p, "r+b") as f:
                f.seek(5)
                f.write(b"\xde\xad\xbe\xef")
        assert load_checkpoint(path, template) is None

    def test_legacy_footerless_checkpoint_still_loads(self, tmp_path):
        import flax.serialization
        path = str(tmp_path / "legacy.ckpt")
        state = {"params": {"w": jnp.arange(3.0)}, "step": jnp.int32(5)}
        payload = flax.serialization.msgpack_serialize(
            flax.serialization.to_state_dict(jax.device_get(state)))
        with open(path, "wb") as f:
            f.write(payload)  # pre-footer format
        restored = load_checkpoint(path, jax.device_get(state))
        assert int(restored["step"]) == 5


class _RecordingIterator:
    def __init__(self):
        self.requests = []

    def update_resource_requirement(self, big_bs, small_bs):
        self.requests.append((big_bs, small_bs))


class TestAdaptationMonitors:
    def test_accordion_requests_big_when_stable(self):
        it = _RecordingIterator()
        mon = AccordionMonitor(it, launch_bs=32, max_bs=256, threshold=0.5)
        for _ in range(10):
            mon.observe_step(1.0)
        assert not mon.end_epoch()  # first epoch: no baseline yet
        for _ in range(10):
            mon.observe_step(1.01)  # stable gradient -> out of critical regime
        assert mon.end_epoch()
        assert it.requests == [(True, False)]

    def test_accordion_requests_small_when_critical(self):
        it = _RecordingIterator()
        mon = AccordionMonitor(it, launch_bs=256, max_bs=256, threshold=0.5)
        for _ in range(10):
            mon.observe_step(1.0)
        mon.end_epoch()
        for _ in range(10):
            mon.observe_step(5.0)  # gradient swinging -> critical regime
        assert mon.end_epoch()
        assert it.requests == [(False, True)]

    def test_gns_requests_double_when_noise_dominates(self):
        it = _RecordingIterator()
        mon = GNSMonitor(it, small_bs=4, big_bs=32, max_bs=256, window=5)
        # E|G_b|^2 = |G|^2 + S/b with |G|^2=1, S=400:
        # small(4) -> 101, big(32) -> 13.5; noise scale 400 >> bs 32.
        for _ in range(5):
            mon.observe_step(small_norm_sq=101.0, big_norm_sq=13.5)
        assert mon.maybe_request_double(current_bs=32)
        assert it.requests == [(True, False)]

    def test_gns_quiet_when_gradient_dominates(self):
        it = _RecordingIterator()
        mon = GNSMonitor(it, small_bs=4, big_bs=32, max_bs=256, window=5)
        # |G|^2=1, S=4: small(4) -> 2.0, big(32) -> 1.125; noise 4 < bs 32.
        for _ in range(5):
            mon.observe_step(small_norm_sq=2.0, big_norm_sq=1.125)
        assert not mon.maybe_request_double(current_bs=32)
        assert it.requests == []


@pytest.mark.slow
class TestWorkloadEntrypoints:
    ENTRIES = [
        ("image_classification/cifar10/main.py",
         ["--batch_size", "32", "--num_steps", "3"]),
        ("image_classification/imagenet/main.py",
         ["-b", "16", "x", "--num_minibatches", "2"]),
        ("translation/train.py",
         ["-data", "x", "-batch_size", "16", "-proj_share_weight", "-step", "2"]),
        ("language_modeling/main.py",
         ["--cuda", "--batch_size", "10", "--steps", "3"]),
        ("recommendation/train.py",
         ["--data_dir", "x", "--batch_size", "512", "-n", "2"]),
        ("rl/main.py",
         ["--workers", "2", "--unroll", "4", "--max-steps", "2"]),
        ("cyclegan/cyclegan.py",
         ["--batch_size", "1", "--img_size", "32", "--n_steps", "2"]),
    ]

    @pytest.mark.parametrize("script,args", ENTRIES,
                             ids=[e[0].split("/")[-2] for e in ENTRIES])
    def test_entry_runs(self, script, args, tmp_path):
        # cpu_subprocess_env, not os.environ + JAX_PLATFORMS: the child
        # must also drop the accelerator relay address, or a wedged
        # relay tunnel hangs its jax import until the test times out.
        from conftest import cpu_subprocess_env
        out = subprocess.run(
            [sys.executable, os.path.join(WORKLOADS, script), *args,
             "--checkpoint_dir", str(tmp_path)],
            capture_output=True, text=True, timeout=900,
            env=cpu_subprocess_env())
        assert out.returncode == 0, out.stderr[-2000:]
        assert "TRAINED" in out.stdout


class TestA3C:
    def test_env_step_and_reward(self):
        from shockwave_tpu.models.a3c import (GRID_H, env_observe, env_reset,
                                              env_step)
        rng = jax.random.PRNGKey(0)
        state = env_reset(rng, 4)
        obs = env_observe(state)
        assert obs.shape == (4, GRID_H, 16, 2)
        # Drop the ball to the bottom: exactly one terminal +-1 per env.
        rewards = []
        for _ in range(GRID_H - 1):
            state, r, done = env_step(state, jnp.ones((4,), jnp.int32))
            rewards.append(np.asarray(r))
        total = np.sum(np.abs(np.stack(rewards)), axis=0)
        np.testing.assert_array_equal(total, np.ones(4))
        # Auto-reset: ball back near the top.
        assert int(jnp.max(state.ball_y)) <= 1

    def test_update_improves_or_runs(self):
        import optax

        from shockwave_tpu.models.a3c import (ActorCritic, build_a3c_update,
                                              env_observe, env_reset)
        model = ActorCritic(hidden=32)
        rng = jax.random.PRNGKey(0)
        env_state = env_reset(rng, 4)
        params = model.init(rng, env_observe(env_state))["params"]
        tx = optax.adam(1e-3)
        ts = {"params": params, "opt_state": tx.init(params), "rng": rng,
              "step": jnp.zeros((), jnp.int32)}
        update = build_a3c_update(model, tx, unroll=8)
        for _ in range(3):
            ts, env_state, metrics = update(ts, env_state)
        assert int(ts["step"]) == 3
        assert np.isfinite(float(metrics["loss"]))


class TestCycleGAN:
    def test_generators_and_discriminators(self):
        from shockwave_tpu.models.cyclegan import Discriminator, Generator
        g, d = Generator(base_features=8, num_blocks=1), Discriminator(base_features=8)
        rng = jax.random.PRNGKey(0)
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        gp = g.init(rng, x)["params"]
        dp = d.init(rng, x)["params"]
        y = g.apply({"params": gp}, x)
        assert y.shape == x.shape and y.dtype == jnp.float32
        assert float(jnp.max(jnp.abs(y))) <= 1.0
        logits = d.apply({"params": dp}, x)
        assert logits.shape[0] == 2 and logits.shape[-1] == 1


class TestRealDataLoaders:
    def _write_cifar(self, root, n=64):
        import pickle as pkl

        import numpy as np
        d = root / "cifar-10-batches-py"
        d.mkdir()
        per = max(1, n // 5)
        for i in range(1, 6):
            batch = {b"data": (np.arange(per * 3072) % 255).astype(
                         np.uint8).reshape(per, 3072),
                     b"labels": [i % 10] * per}
            with open(d / f"data_batch_{i}", "wb") as f:
                pkl.dump(batch, f)
        return root

    def test_cifar10_real(self, tmp_path):
        from shockwave_tpu.models import data
        root = self._write_cifar(tmp_path)
        loader = data.cifar10(4, data_dir=str(root))
        assert not loader.synthetic
        images, labels = next(iter(loader))
        assert images.shape == (4, 32, 32, 3)
        assert images.dtype.name == "float32"
        assert 0.0 <= images.min() and images.max() <= 1.0
        assert labels.shape == (4,)
        # Two epochs reshuffle: union over one epoch covers the data.
        assert len(loader) == 60 // 4

    def test_cifar10_fallback_when_missing(self, tmp_path):
        from shockwave_tpu.models import data
        loader = data.cifar10(4, data_dir=str(tmp_path / "nope"))
        assert loader.synthetic

    def test_wikitext2_real(self, tmp_path):
        from shockwave_tpu.models import data
        text = " ".join(f"word{i % 50}" for i in range(5000))
        (tmp_path / "wiki.train.tokens").write_text(text)
        loader = data.wikitext2(2, seq_len=10, data_dir=str(tmp_path))
        assert not loader.synthetic
        tokens, targets = next(iter(loader))
        assert tokens.shape == (2, 10) and targets.shape == (2, 10)
        # LM shift: target is the next token of the same stream.
        assert (tokens[:, 1:] == targets[:, :-1]).all()

    def test_multi30k_real(self, tmp_path):
        from shockwave_tpu.models import data
        de = "\n".join(f"ein kleines wort{i % 30} satz" for i in range(40))
        en = "\n".join(f"a small word{i % 30} sentence" for i in range(40))
        (tmp_path / "train.de").write_text(de)
        (tmp_path / "train.en").write_text(en)
        loader = data.multi30k(4, src_len=8, tgt_len=9,
                               data_dir=str(tmp_path))
        assert not loader.synthetic
        src, tgt = next(iter(loader))
        assert src.shape == (4, 8) and tgt.shape == (4, 9)
        # Targets wrapped BOS ... EOS; sources unwrapped.
        assert (tgt[:, 0] == data.BOS).all()
        assert (tgt == data.EOS).any(axis=1).all()

    def test_multi30k_accepts_reference_pt_path(self, tmp_path):
        """The trace passes the reference's preprocessed .pt file path;
        the loader must fall back to the raw pair files beside it."""
        from shockwave_tpu.models import data
        (tmp_path / "train.de").write_text("ein satz\n" * 8)
        (tmp_path / "train.en").write_text("a sentence\n" * 8)
        loader = data.multi30k(
            2, data_dir=str(tmp_path / "multi30k.atok.low.pt"))
        assert not loader.synthetic

    def test_ml20m_real(self, tmp_path):
        from shockwave_tpu.models import data

        import numpy as np
        d = tmp_path / "pro_sg"
        d.mkdir()
        lines = ["uid,sid"]
        for uid in range(12):
            for sid in range(uid % 4 + 1):
                lines.append(f"{uid},{sid * 7 % 19}")
        (d / "train.csv").write_text("\n".join(lines))
        loader = data.ml20m(4, num_items=19, data_dir=str(tmp_path))
        assert not loader.synthetic
        (rows,) = next(iter(loader))
        assert rows.shape == (4, 19)
        assert set(np.unique(rows)) <= {0.0, 1.0}
        assert rows.sum() >= 4  # every user has >= 1 interaction

    def test_ml20m_caps_items_by_frequency(self, tmp_path):
        from shockwave_tpu.models import data
        d = tmp_path / "pro_sg"
        d.mkdir()
        # Item 500 appears in every row (most frequent); item 900 once.
        lines = ["uid,sid"] + [f"{u},500" for u in range(8)] + ["0,900"]
        (d / "train.csv").write_text("\n".join(lines))
        loader = data.ml20m(2, num_items=1, data_dir=str(tmp_path))
        assert not loader.synthetic
        (rows,) = next(iter(loader))
        assert rows.shape == (2, 1)
        assert rows.sum() == 2  # the kept item is the frequent one

    def test_monet2photo_real_npz(self, tmp_path):
        from shockwave_tpu.models import data

        import numpy as np
        a = np.random.RandomState(0).randint(
            0, 255, size=(6, 16, 16, 3)).astype(np.float32)
        b = np.random.RandomState(1).randint(
            0, 255, size=(9, 16, 16, 3)).astype(np.float32)
        np.savez(tmp_path / "monet2photo.npz", A=a, B=b)
        loader = data.monet2photo(3, image_size=16, data_dir=str(tmp_path))
        assert not loader.synthetic
        xa, xb = next(iter(loader))
        assert xa.shape == (3, 16, 16, 3) and xb.shape == (3, 16, 16, 3)
        assert -1.0 <= xa.min() and xa.max() <= 1.0
        assert len(loader) == 6 // 3
        # Stored size != requested size -> resampled, not crashed.
        loader8 = data.monet2photo(3, image_size=8, data_dir=str(tmp_path))
        xa8, _ = next(iter(loader8))
        assert xa8.shape == (3, 8, 8, 3)

    def test_imagenet_real_folder(self, tmp_path):
        pytest.importorskip("PIL")
        from PIL import Image

        import numpy as np
        from shockwave_tpu.models import data
        root = tmp_path / "imagenet" / "train"
        for ci, cls in enumerate(("n01440764", "n01443537")):
            d = root / cls
            d.mkdir(parents=True)
            for i in range(4):
                arr = np.full((30, 40, 3), 40 * ci + i, dtype="uint8")
                Image.fromarray(arr).save(d / f"im{i}.jpg")
        loader = data.imagenet(4, data_dir=str(tmp_path / "imagenet"))
        assert not loader.synthetic
        assert len(loader) == 8 // 4
        images, labels = next(iter(loader))
        assert images.shape == (4, 224, 224, 3)
        assert images.dtype.name == "float32"
        assert 0.0 <= images.min() and images.max() <= 1.0
        assert set(labels.tolist()) <= {0, 1}

    def test_imagenet_fallback_when_missing(self, tmp_path):
        from shockwave_tpu.models import data
        assert data.imagenet(4, data_dir=str(tmp_path / "nope")).synthetic

    def test_monet2photo_real_folders(self, tmp_path):
        PIL = pytest.importorskip("PIL")
        from PIL import Image

        import numpy as np
        from shockwave_tpu.models import data
        for dom, n in (("trainA", 4), ("trainB", 5)):
            d = tmp_path / dom
            d.mkdir()
            for i in range(n):
                arr = np.random.RandomState(i).randint(
                    0, 255, size=(20, 24, 3)).astype("uint8")
                Image.fromarray(arr).save(d / f"img{i}.jpg")
        loader = data.monet2photo(2, image_size=16, data_dir=str(tmp_path))
        assert not loader.synthetic
        xa, xb = next(iter(loader))
        assert xa.shape == (2, 16, 16, 3) and xb.shape == (2, 16, 16, 3)

    def test_cifar10_workload_trains_on_real_data(self, tmp_path):
        """End-to-end: the dispatched CLI trains on a real data_dir."""
        import subprocess
        import sys

        from conftest import cpu_subprocess_env
        root = self._write_cifar(tmp_path)
        out = subprocess.run(
            [sys.executable,
             "shockwave_tpu/workloads/image_classification/cifar10/main.py",
             "--data_dir", str(root), "--batch_size", "8",
             "--num_steps", "3",
             "--checkpoint_dir", str(tmp_path / "ckpt")],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.join(os.path.dirname(__file__), ".."),
            env=cpu_subprocess_env())
        assert out.returncode == 0, out.stderr[-2000:]
        assert "TRAINED 3 steps" in out.stdout


class TestCompileCache:
    def test_cache_dir_is_host_fingerprinted(self, tmp_path):
        """XLA:CPU AOT artifacts embed the compile machine's feature set
        and fail to load elsewhere; the persistent cache must segregate
        executables per host fingerprint."""
        from shockwave_tpu.models import train_common as tc

        old = jax.config.jax_compilation_cache_dir
        try:
            tc.enable_compile_cache(str(tmp_path / "xc"))
            got = jax.config.jax_compilation_cache_dir
            assert os.path.dirname(got) == str(tmp_path / "xc")
            fp = os.path.basename(got)
            assert fp == tc._host_fingerprint()
            assert len(fp) == 8
            assert os.path.isdir(got)
            # Fingerprint is stable across calls on the same host.
            assert tc._host_fingerprint() == fp
        finally:
            jax.config.update("jax_compilation_cache_dir", old)
