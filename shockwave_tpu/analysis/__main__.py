"""CLI driver: ``python -m shockwave_tpu.analysis [--root R] [--select a,b]``.

Runs every pass (or the ``--select``ed subset) over the repo tree and
prints findings as ``path:line: [pass-id] message``. Exit status: 0 on
a clean tree, 1 when any finding survives, 2 on usage errors.

The tier-1 gate (tests/test_analysis.py) runs exactly this entry
point, so CI and a local ``scripts/utils/check.py`` see the same
verdict.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import Finding, RepoIndex
from .passes import ALL_PASSES

#: Repo-relative directories scanned by default.
DEFAULT_INCLUDE_DIRS = ("shockwave_tpu", "scripts")
#: Generated code is not ours to lint.
DEFAULT_EXCLUDE_GLOBS = ("shockwave_tpu/runtime/proto/*",)


def default_root() -> str:
    """The repo root: the directory holding the shockwave_tpu package."""
    package_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def run(root: Optional[str] = None,
        select: Optional[List[str]] = None) -> List[Finding]:
    """Run the selected passes with repo-default scopes; returns the
    combined findings sorted by location."""
    index = RepoIndex.from_root(root or default_root(),
                                include_dirs=DEFAULT_INCLUDE_DIRS,
                                exclude_globs=DEFAULT_EXCLUDE_GLOBS)
    findings: List[Finding] = []
    for name in (select or sorted(ALL_PASSES)):
        findings.extend(ALL_PASSES[name](index))
    return sorted(findings, key=lambda f: (f.path, f.line, f.pass_id))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis",
        description=__doc__.splitlines()[0])
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: autodetect "
                             "from the installed package location)")
    parser.add_argument("--select", default=None,
                        help="comma-separated pass ids "
                             f"(default: all of {', '.join(sorted(ALL_PASSES))})")
    parser.add_argument("--list", action="store_true",
                        help="list pass ids and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name, fn in sorted(ALL_PASSES.items()):
            first_line = (fn.__doc__ or "").strip().splitlines()[0]
            print(f"{name}: {first_line}")
        return 0

    select = None
    if args.select:
        select = [p.strip() for p in args.select.split(",") if p.strip()]
        unknown = [p for p in select if p not in ALL_PASSES]
        if unknown:
            print(f"unknown pass id(s): {', '.join(unknown)} "
                  f"(try --list)", file=sys.stderr)
            return 2

    findings = run(root=args.root, select=select)
    for f in findings:
        print(f)
    print(f"swtpu-check: {len(findings)} finding(s)"
          + ("" if findings else " — tree is clean"))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
