"""Seeded obs-discipline propagation violations.

Mirrors the fleet-tracing half of the pass: a reserved span-context
key / shard filename literal copied verbatim outside the name catalog
(the cross-process contract may only be spelled in obs/names.py — the
test's ``good_names.py`` stand-in), and a wall-clock read inside what
the test treats as a span-emitting runtime module
(``clock_extra_globs=("bad_propagation.py",)``) — span timestamps must
come from the injected obs clock.
"""
import time


def forked_metadata_key():
    return ("fixture-traceparent", "00-abc-def-01")  # SEEDED


def forked_shard_prefix():
    return "fixture-spans-" + "worker-1.json"  # SEEDED


def stamp_span_start():
    return time.time()  # SEEDED


def reference_is_fine(names):
    # Attribute references into the catalog are the sanctioned form.
    return names.TRACEPARENT_METADATA_KEY
