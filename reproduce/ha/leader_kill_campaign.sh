#!/usr/bin/env bash
# Committed control-plane HA failover study: 20 seeded leader-kill /
# leader-freeze schedules (real HA leader + hot-standby scheduler
# subprocesses, stub workers, SWTPU_SANITIZE=1), every invariant
# re-derived from the durable journal. Byte-reproducible and resumable:
# re-running against the committed artifact skips completed schedules;
# --restart redoes everything.
#
#   bash reproduce/ha/leader_kill_campaign.sh
#
# Wall time ~3-5 min on a laptop-class CPU host (schedules run
# sequentially; each is a full failover drive).
set -euo pipefail
cd "$(dirname "$0")/../.."

python scripts/drivers/chaos_campaign.py \
    --trace data/canonical_120job.trace \
    --policy max_min_fairness \
    --throughputs data/tacc_throughputs.json \
    --cluster_spec v100:8 --round_duration 120 \
    --num_schedules 0 --ha_schedules 20 \
    --out reproduce/ha/leader_kill_campaign.json \
    --workdir "${SWTPU_HA_WORKDIR:-/tmp/swtpu_ha_campaign}" \
    --timing_out reproduce/ha/leader_kill_campaign.timing.json \
    "$@"
