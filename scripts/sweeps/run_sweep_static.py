#!/usr/bin/env python3
"""Static-trace sweep: policies x job-count, all jobs arriving at t=0.

The "fixed batch of jobs, vary the batch size" experiment — isolates
scheduling quality from arrival dynamics
(reference: scheduler/scripts/sweeps/run_sweep_static.py).

Example:
    python scripts/sweeps/run_sweep_static.py \
        --policies max_min_fairness isolated --num_jobs_list 32 64 128
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sweep_common import add_common_args, run_sweep


def main():
    p = add_common_args(argparse.ArgumentParser(description=__doc__))
    p.add_argument("--num_jobs_list", nargs="*", type=int,
                   default=[32, 64, 128])
    args = p.parse_args()
    run_sweep(args.policies, args.num_jobs_list, [0.0], args.seeds,
              args.throughputs, args.cluster_spec, args.round_duration,
              args.config, args.output)


if __name__ == "__main__":
    main()
