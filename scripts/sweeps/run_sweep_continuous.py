#!/usr/bin/env python3
"""Continuous-arrival sweep: policies x Poisson load levels.

Sweeps the mean interarrival time (`--lams`, seconds) at a fixed job
count — the "vary cluster load, watch JCT/fairness degrade" experiment
(reference: scheduler/scripts/sweeps/run_sweep_continuous.py).

Example:
    python scripts/sweeps/run_sweep_continuous.py \
        --policies max_min_fairness fifo --num_jobs 64 --lams 1800 600 300
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sweep_common import add_common_args, run_sweep


def main():
    p = add_common_args(argparse.ArgumentParser(description=__doc__))
    p.add_argument("--num_jobs", type=int, default=64)
    p.add_argument("--lams", nargs="*", type=float,
                   default=[3600.0, 1800.0, 900.0, 450.0])
    args = p.parse_args()
    run_sweep(args.policies, [args.num_jobs], args.lams, args.seeds,
              args.throughputs, args.cluster_spec, args.round_duration,
              args.config, args.output)


if __name__ == "__main__":
    main()
