"""gRPC runtime tests: loopback RPC roundtrips and a full physical-mode
round pipeline with stub workers (no subprocesses)."""
import os
import socket
import threading
import time

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.runtime.clients import (IteratorToSchedulerClient,
                                           SchedulerToWorkerClient,
                                           WorkerToSchedulerClient)
from shockwave_tpu.runtime.servers import serve_scheduler, serve_worker
from shockwave_tpu.sched.physical import PhysicalScheduler
from shockwave_tpu.sched.scheduler import SchedulerConfig
from shockwave_tpu.solver import get_policy

DATA = os.path.join(os.path.dirname(__file__), "..", "data")


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class TestRpcRoundtrips:
    def test_register_and_done(self):
        port = free_port()
        calls = {}

        def register(worker_type, num_chips, ip_addr, port):
            calls["register"] = (worker_type, num_chips)
            return [0, 1, 2, 3], 120.0

        def done(job_id, worker_id, num_steps, times, logs):
            calls["done"] = (job_id, worker_id, num_steps, times)

        server = serve_scheduler(port, {
            "RegisterWorker": register, "Done": done,
            "InitJob": lambda job_id: (100, 60.0, 0.0),
            "UpdateLease": lambda *a: (200, 120.0, 5.0, 1000.0),
            "UpdateResourceRequirement": lambda *a: None,
        })
        try:
            client = WorkerToSchedulerClient("localhost", port)
            worker_ids, round_duration = client.register_worker(
                "v5e", "127.0.0.1", 12345, 4)
            assert worker_ids == [0, 1, 2, 3]
            assert round_duration == 120.0
            assert calls["register"] == ("v5e", 4)

            client.notify_done([7], 2, [500], [60.0], ["log"])
            assert calls["done"][0] == JobIdPair(7)
            assert calls["done"][2] == [500]

            it = IteratorToSchedulerClient(7, 2, "localhost", port)
            assert it.init() == (100, 60.0, 0.0)
            assert it.update_lease(10, 5.0, 100, 60.0) == (200, 120.0, 5.0, 1000.0)
        finally:
            server.stop(grace=0)

    def test_worker_server_run_job(self):
        port = free_port()
        received = {}

        def run_job(jobs, worker_id, round_id):
            received["jobs"] = jobs
            received["worker_id"] = worker_id

        server = serve_worker(port, {
            "RunJob": run_job, "KillJob": lambda j: received.update(killed=j),
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        try:
            client = SchedulerToWorkerClient("localhost", port)
            client.run_job([dict(job_id=3, command="python3 train.py",
                                 working_directory="wd", needs_data_dir=False,
                                 num_steps_arg="--steps", num_steps=1000,
                                 mode="static")], worker_id=1, round_id=0)
            assert received["jobs"][0]["job_id"] == 3
            assert received["jobs"][0]["num_steps"] == 1000
            client.kill_job(3)
            assert received["killed"] == 3
        finally:
            server.stop(grace=0)


class TestLeaseIterator:
    def test_lease_expiry_and_renewal(self, tmp_path, monkeypatch):
        port = free_port()
        lease_calls = []

        def update_lease(job_id, worker_id, steps, duration, max_steps,
                         max_duration):
            lease_calls.append(steps)
            # Grant 50 more steps each renewal, up to 150 total.
            new_max = min(int(max_steps) + 50, 150)
            return (new_max, 1e6, 0.0, 1e9)

        server = serve_scheduler(port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": lambda job_id: (100, 1e6, 0.0),
            "UpdateLease": update_lease,
            "UpdateResourceRequirement": lambda *a: None,
        })
        monkeypatch.setenv("SWTPU_JOB_ID", "0")
        monkeypatch.setenv("SWTPU_WORKER_ID", "0")
        monkeypatch.setenv("SWTPU_ROUND_ID", "0")
        monkeypatch.setenv("SWTPU_SCHED_ADDR", "localhost")
        monkeypatch.setenv("SWTPU_SCHED_PORT", str(port))
        try:
            from shockwave_tpu.runtime.iterator import LeaseIterator
            it = LeaseIterator(
                data_loader=list(range(10)), checkpoint_dir=str(tmp_path),
                load_checkpoint_func=lambda p: None,
                save_checkpoint_func=lambda p, s: None,
                synthetic_data=True)
            consumed = 0
            for _ in range(30):  # epochs over synthetic data (10 steps each)
                try:
                    for _ in it:
                        consumed += 1
                except StopIteration:
                    pass
                if it.done:
                    break
            # Lease capped at 150 steps; iterator must stop at/near it.
            assert it.done
            assert consumed <= 150
            assert consumed >= 100  # ran past the initial lease via renewals
            assert len(lease_calls) >= 1  # renewal happened at 75% boundary
        finally:
            server.stop(grace=0)

    def test_degrade_factor_throttles_step_rate(self, tmp_path,
                                                monkeypatch):
        """SWTPU_DEGRADE_FACTOR (exported by the dispatcher when a
        `degrade` fault covers the dispatch) must genuinely slow the
        job: each step is padded to compute/factor while leases keep
        renewing — the gray failure made real for actual trainers."""
        port = free_port()
        server = serve_scheduler(port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": lambda job_id: (1000, 1e6, 0.0),
            "UpdateLease": lambda *a: (1000, 1e6, 0.0, 1e9),
            "UpdateResourceRequirement": lambda *a: None,
        })
        monkeypatch.setenv("SWTPU_JOB_ID", "0")
        monkeypatch.setenv("SWTPU_WORKER_ID", "0")
        monkeypatch.setenv("SWTPU_ROUND_ID", "0")
        monkeypatch.setenv("SWTPU_SCHED_ADDR", "localhost")
        monkeypatch.setenv("SWTPU_SCHED_PORT", str(port))
        try:
            from shockwave_tpu.runtime.iterator import LeaseIterator

            def run_steps(factor, n=12, step_time=0.01):
                if factor is None:
                    monkeypatch.delenv("SWTPU_DEGRADE_FACTOR",
                                       raising=False)
                else:
                    monkeypatch.setenv("SWTPU_DEGRADE_FACTOR",
                                       str(factor))
                it = LeaseIterator(
                    data_loader=list(range(1000)),
                    checkpoint_dir=str(tmp_path),
                    load_checkpoint_func=lambda p: None,
                    save_checkpoint_func=lambda p, s: None,
                    synthetic_data=False, write_on_close=False)
                iter(it)
                t0 = time.time()
                for _ in range(n):
                    next(it)
                    time.sleep(step_time)  # the "compute"
                return time.time() - t0

            full = run_steps(None)
            slow = run_steps(0.25)
            # At factor 0.25 each step is padded ~4x; allow generous
            # slack for timer noise but require a clear slowdown.
            assert slow > 2.0 * full, (full, slow)
            # Garbage values fall back to full speed, not a crash.
            garbage = run_steps("not-a-number")
            assert garbage < 2.0 * full, (full, garbage)
        finally:
            server.stop(grace=0)


    def test_async_runahead_bounded_and_renewal_timely(self, tmp_path,
                                                       monkeypatch):
        """Regression: JAX async dispatch let the Python loop race to the
        steps-based renewal threshold in seconds, then the renewal's
        device sync drained the whole dispatched backlog (minutes for
        slow-step models) before the renewal RPC — the only heartbeat —
        was sent, so the scheduler killed the job as unresponsive. The
        run-ahead window must keep dispatch within SWTPU_RUNAHEAD_STEPS
        of the device so every sync is short and renewals are timely."""
        port = free_port()
        step_time = 0.04
        t0 = time.time()
        renewal_walls = []

        def update_lease(job_id, worker_id, steps, duration, max_steps,
                         max_duration):
            renewal_walls.append(time.time() - t0)
            return (int(max_steps), float(max_duration), 0.0, 1e9)  # deny

        server = serve_scheduler(port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            # 500-step lease, 1.2 s max duration: time expiry must win.
            "InitJob": lambda job_id: (500, 1.2, 0.0),
            "UpdateLease": update_lease,
            "UpdateResourceRequirement": lambda *a: None,
        })
        monkeypatch.setenv("SWTPU_JOB_ID", "0")
        monkeypatch.setenv("SWTPU_WORKER_ID", "0")
        monkeypatch.setenv("SWTPU_ROUND_ID", "0")
        monkeypatch.setenv("SWTPU_SCHED_ADDR", "localhost")
        monkeypatch.setenv("SWTPU_SCHED_PORT", str(port))
        monkeypatch.setenv("SWTPU_RUNAHEAD_STEPS", "4")

        from shockwave_tpu.runtime import iterator as iterator_mod

        def fake_device_sync(ref):
            # The simulated device finishes step i at t0 + (i+1)*step_time;
            # syncing on step i's ref waits until then.
            if ref is None:
                return
            done_at = t0 + (ref[0] + 1) * step_time
            wait = done_at - time.time()
            if wait > 0:
                time.sleep(wait)

        monkeypatch.setattr(iterator_mod, "_device_sync", fake_device_sync)
        try:
            it = iterator_mod.LeaseIterator(
                data_loader=list(range(1000)), checkpoint_dir=str(tmp_path),
                load_checkpoint_func=lambda p: None,
                save_checkpoint_func=lambda p, s: None,
                synthetic_data=True)
            dispatched = 0
            try:
                for _ in it:
                    # Python dispatch is instant; the device is not.
                    it.set_sync_ref([dispatched])
                    dispatched += 1
            except StopIteration:
                pass
            total_wall = time.time() - t0
            assert it.done
            # Expiry by time (~1.2 s) plus a <= runahead-deep drain — not
            # after draining a hundreds-deep backlog (>= 10 s pre-fix).
            assert total_wall < 3.0, total_wall
            # Dispatch stayed within the window of the device: ~30 real
            # steps fit in the lease; 500 would mean unbounded run-ahead.
            assert dispatched <= 1.2 / step_time + 10, dispatched
            # The renewal heartbeat went out near the 75% lease point.
            assert renewal_walls and renewal_walls[0] < 2.0, renewal_walls
        finally:
            server.stop(grace=0)


class StubWorkerDaemon:
    """In-process worker: simulates job execution at a fixed throughput
    instead of launching training subprocesses."""

    def __init__(self, sched_port, worker_port, num_chips=2,
                 throughput=100.0, execution_time=0.5):
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.server = serve_worker(worker_port, {
            "RunJob": self._run_job, "KillJob": lambda j: None,
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", worker_port, num_chips)

    def _run_job(self, jobs, worker_id, round_id):
        def execute():
            # Mimic the job-side lease iterator: init, run, report.
            for j in jobs:
                it = IteratorToSchedulerClient(j["job_id"], worker_id,
                                               "localhost", self.sched_port)
                max_steps, max_duration, extra = it.init()
            time.sleep(self.execution_time)
            steps = [min(int(self.throughput * self.round_duration),
                         j["num_steps"], int(max_steps)) for j in jobs]
            self._client.notify_done(
                [j["job_id"] for j in jobs], worker_id, steps,
                [self.execution_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    def stop(self):
        self.server.stop(grace=0)


@pytest.mark.runtime
class TestPhysicalRounds:
    def test_end_to_end_rounds(self):
        sched_port = free_port()
        worker_port = free_port()
        policy = get_policy("max_min_fairness")
        sched = PhysicalScheduler(
            policy, throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=3),
            expected_num_workers=2, port=sched_port)
        worker = StubWorkerDaemon(sched_port, worker_port, num_chips=2,
                                  throughput=100.0)
        try:
            # Job needs 150 steps; stub reports min(100*2, 150)=150 in round 0.
            job = Job(None, "ResNet-18 (batch size 32)",
                      "python3 main.py --batch_size 32",
                      "image_classification/cifar10", "--num_steps",
                      total_steps=150, duration=10000)
            sched.add_job(job)
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 1, "job did not complete"
            assert sched.acct.completion_times[JobIdPair(0)] is not None
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)

    def test_two_jobs_share_two_chips(self):
        sched_port = free_port()
        worker_port = free_port()
        policy = get_policy("max_min_fairness")
        sched = PhysicalScheduler(
            policy, throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=4),
            expected_num_workers=2, port=sched_port)
        worker = StubWorkerDaemon(sched_port, worker_port, num_chips=2,
                                  throughput=100.0)
        try:
            for _ in range(2):
                sched.add_job(Job(
                    None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=180, duration=10000))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 40
            while time.time() < deadline:
                if len(sched._completed_jobs) == 2:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 2
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)

    def test_accordion_rescale_through_rpc(self):
        """UpdateResourceRequirement -> done -> bs rescale -> redispatch at
        the new batch size (the physical half of dynamic adaptation)."""
        sched_port = free_port()
        worker_port = free_port()
        policy = get_policy("max_min_fairness")
        sched = PhysicalScheduler(
            policy, throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=4),
            expected_num_workers=1, port=sched_port)

        seen_bs = []

        class AdaptiveStub(StubWorkerDaemon):
            def _run_job(self, jobs, worker_id, round_id):
                def execute():
                    try:
                        for j in jobs:
                            seen_bs.append(j["command"].rsplit(" ", 1)[-1])
                            it = IteratorToSchedulerClient(
                                j["job_id"], worker_id, "localhost",
                                self.sched_port)
                            it.init()
                            if round_id == 0:
                                # First run discovers it can use the max bs.
                                it.update_resource_requirement(big_bs=True,
                                                               small_bs=False)
                        time.sleep(self.execution_time)
                        self._client.notify_done(
                            [j["job_id"] for j in jobs], worker_id,
                            [60] * len(jobs),
                            [self.execution_time] * len(jobs))
                    except Exception:  # noqa: BLE001 - teardown race
                        pass
                threading.Thread(target=execute, daemon=True).start()

        worker = AdaptiveStub(sched_port, worker_port, num_chips=1,
                              throughput=100.0)
        try:
            job = Job(None, "ResNet-18 (batch size 32)",
                      "python3 main.py --batch_size 32",
                      "image_classification/cifar10", "--num_steps",
                      total_steps=100000, duration=10000, mode="accordion")
            sched.add_job(job)
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                if "256" in seen_bs:
                    break
                time.sleep(0.2)
            assert seen_bs and seen_bs[0] == "32"
            assert "256" in seen_bs, f"no rescaled dispatch seen: {seen_bs}"
            assert sched.acct.jobs[JobIdPair(0)].batch_size == 256
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)

    def test_gang_job_consensus_and_completion(self):
        """A scale_factor=2 job is gang-dispatched to both chips; the two
        ranks' lease renewals agree on one step budget (first-requester-
        computes) and the job completes from aggregated reports."""
        sched_port = free_port()
        worker_port = free_port()
        policy = get_policy("max_min_fairness")
        sched = PhysicalScheduler(
            policy, throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=3),
            expected_num_workers=2, port=sched_port)

        consensus_budgets = []
        commands = []

        class GangStub(StubWorkerDaemon):
            def _run_job(self, jobs, worker_id, round_id):
                def execute():
                    try:
                        for j in jobs:
                            commands.append(j["command"])
                            it = IteratorToSchedulerClient(
                                j["job_id"], worker_id, "localhost",
                                self.sched_port)
                            it.init()
                            max_steps, _, _, _ = it.update_lease(
                                steps=40, duration=0.5, max_steps=10**9,
                                max_duration=10**9)
                            consensus_budgets.append(max_steps)
                        time.sleep(self.execution_time)
                        self._client.notify_done(
                            [j["job_id"] for j in jobs], worker_id,
                            [75] * len(jobs),
                            [self.execution_time] * len(jobs))
                    except Exception:  # noqa: BLE001 - teardown race
                        pass
                threading.Thread(target=execute, daemon=True).start()

        worker = GangStub(sched_port, worker_port, num_chips=2,
                          throughput=100.0)
        try:
            # 150 total steps over 2 chips: each rank reports 75.
            job = Job(None, "ResNet-18 (batch size 32)",
                      "python3 main.py --batch_size 32",
                      "image_classification/cifar10", "--num_steps",
                      total_steps=150, duration=10000, scale_factor=2)
            sched.add_job(job)
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 1, "gang job did not complete"
            # Both ranks were dispatched with rendezvous info.
            assert len(commands) >= 2
            assert all("--coordinator" in c and "--num_processes 2" in c
                       for c in commands[:2])
            ranks = sorted(int(c.rsplit("--process_id ", 1)[1].split()[0])
                           for c in commands[:2])
            assert ranks == [0, 1]
            # First-requester-computes: both ranks got the same budget.
            assert len(set(consensus_budgets[:2])) == 1
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)


class TestDispatcherEnv:
    def test_job_env_injects_mode(self, tmp_path):
        from shockwave_tpu.runtime.dispatcher import Dispatcher
        d = Dispatcher(round_duration=1.0, chip_ids=[0],
                       worker_rpc_client=None, sched_addr="127.0.0.1",
                       sched_port=1234, run_dirs={}, data_dir=None,
                       checkpoint_dir=str(tmp_path))
        env = d._job_env({"job_id": 7, "mode": "accordion"}, worker_id=0,
                         round_id=0, chip_id=0)
        assert env["SWTPU_MODE"] == "accordion"
        env = d._job_env({"job_id": 8, "mode": ""}, worker_id=0,
                         round_id=0, chip_id=0)
        assert env["SWTPU_MODE"] == "static"


class TestWorkerRegisterRetry:
    """Daemons race the scheduler at cluster bring-up; registration must
    retry through connection refusals instead of dying."""

    def test_retries_until_scheduler_appears(self, monkeypatch, tmp_path):
        from shockwave_tpu.runtime import worker as worker_mod
        monkeypatch.setattr(worker_mod, "REGISTER_RETRY_INTERVAL_S", 0.2)
        sched_port = free_port()
        box = {}

        def start_sched_late():
            time.sleep(1.0)
            box["server"] = serve_scheduler(sched_port, {
                "RegisterWorker":
                    lambda worker_type, num_chips, ip_addr, port: ([0], 60.0),
            })

        t = threading.Thread(target=start_sched_late)
        t.start()
        daemon = None
        try:
            daemon = worker_mod.WorkerDaemon(
                worker_type="cpu", sched_addr="127.0.0.1",
                sched_port=sched_port, worker_port=free_port(), num_chips=1,
                run_dirs={"static": ".", "accordion": ".", "gns": "."},
                data_dir=str(tmp_path), checkpoint_dir=str(tmp_path / "ckpt"))
            assert daemon._worker_ids == [0]
        finally:
            t.join()
            if daemon is not None:
                daemon._server.stop(grace=0)
            if "server" in box:
                box["server"].stop(grace=0)

    def test_gives_up_after_retry_window(self, monkeypatch, tmp_path):
        import grpc

        from shockwave_tpu.runtime import worker as worker_mod
        monkeypatch.setattr(worker_mod, "REGISTER_RETRY_INTERVAL_S", 0.1)
        monkeypatch.setattr(worker_mod, "REGISTER_RETRY_WINDOW_S", 0.4)
        with pytest.raises(grpc.RpcError):
            worker_mod.WorkerDaemon(
                worker_type="cpu", sched_addr="127.0.0.1",
                sched_port=free_port(), worker_port=free_port(), num_chips=1,
                run_dirs={"static": ".", "accordion": ".", "gns": "."},
                data_dir=str(tmp_path), checkpoint_dir=str(tmp_path / "ckpt"))


@pytest.mark.runtime
class TestExtendedLeaseLiveness:
    def _make_sched(self):
        port = free_port()
        return PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=100.0),
            expected_num_workers=1, port=port)

    def test_missing_heartbeat_entry_does_not_kill(self):
        """A member with no heartbeat stamp (e.g. the already-completed
        half of a packed pair) must default to `now`, not 0.0 — a 0.0
        default reads as an epoch-old heartbeat and kills the survivor."""
        sched = self._make_sched()
        try:
            job = Job(None, "ResNet-18 (batch size 32)",
                      "python3 main.py --batch_size 32",
                      "image_classification/cifar10", "--num_steps",
                      total_steps=100, duration=1000)
            job_id = sched.add_job(job)
            kills = []
            sched._kill_job = lambda j: kills.append(j)
            assert job_id not in sched._last_heartbeat
            sched._done_callback_extended_lease(job_id)
            assert kills == []
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_stale_heartbeat_kills(self):
        sched = self._make_sched()
        try:
            job = Job(None, "ResNet-18 (batch size 32)",
                      "python3 main.py --batch_size 32",
                      "image_classification/cifar10", "--num_steps",
                      total_steps=100, duration=1000)
            job_id = sched.add_job(job)
            kills = []
            sched._kill_job = lambda j: kills.append(j)
            sched._last_heartbeat[job_id] = (
                sched.get_current_timestamp() - 10_000.0)
            sched._done_callback_extended_lease(job_id)
            assert kills == [job_id]
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


@pytest.mark.runtime
class TestFirstInitGrace:
    """A freshly dispatched job that has not yet reached its first RPC is
    re-armed, not killed: cold dispatch through a relayed TPU can wait
    minutes for the chip grant, and SIGKILLing the waiter wedges the
    relay so the NEXT dispatch hangs too (observed live on the v5e
    tunnel)."""

    def _make_sched(self, **cfg):
        return PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=100.0, **cfg),
            expected_num_workers=1, port=free_port())

    def _add_dispatched_job(self, sched):
        job = Job(None, "ResNet-18 (batch size 32)",
                  "python3 main.py --batch_size 32",
                  "image_classification/cifar10", "--num_steps",
                  total_steps=100, duration=1000)
        job_id = sched.add_job(job)
        sched.rounds.current_assignments[job_id] = (0,)
        sched._last_heartbeat[job_id] = sched.get_current_timestamp()
        return job_id

    def test_never_signaled_job_rearms_within_grace(self):
        sched = self._make_sched(first_init_grace_s=300.0)
        try:
            job_id = self._add_dispatched_job(sched)
            assert job_id not in sched._ever_signaled
            sched._kill_job(job_id)  # no worker connections: would raise
            timer = sched._completion_events.get(job_id)
            assert timer is not None, "grace must re-arm the kill timer"
            timer.cancel()
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_fresh_heartbeat_rearms_even_after_init(self):
        sched = self._make_sched(first_init_grace_s=300.0)
        try:
            job_id = self._add_dispatched_job(sched)
            sched._ever_signaled.add(job_id)  # first RPC just landed
            sched._kill_job(job_id)
            timer = sched._completion_events.get(job_id)
            assert timer is not None, "fresh heartbeat must re-arm"
            timer.cancel()
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_stale_signaled_job_is_killed(self):
        # kill_wait_s keeps the kill path's real _cv.wait short; stubbing
        # the condition's wait would make the allocation thread's waits
        # into lock-holding spins.
        sched = self._make_sched(first_init_grace_s=300.0, kill_wait_s=0.1)
        try:
            job_id = self._add_dispatched_job(sched)
            sched._ever_signaled.add(job_id)
            sched._last_heartbeat[job_id] -= 10_000.0

            class _StubClient:
                addr, port = "127.0.0.1", 0
                killed = []

                def kill_job(self, int_id):
                    self.killed.append(int_id)

            sched._worker_connections[0] = _StubClient()
            done = []
            sched.done_callback = lambda *a: done.append(a)
            sched._kill_job(job_id)
            assert _StubClient.killed == [job_id.integer_job_id()]
            assert done, "missing workers must get a zero-step done"
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


@pytest.mark.runtime
class TestInitLeaseFloor:
    """A job whose startup (imports + jit) eats most of the round must not
    be granted a sliver lease that expires before one step — that
    livelocks the job re-paying startup every round."""

    def _make_sched(self, round_duration=100.0):
        return PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=round_duration),
            expected_num_workers=1, port=free_port())

    def _add_job(self, sched):
        job = Job(None, "ResNet-18 (batch size 32)",
                  "python3 main.py --batch_size 32",
                  "image_classification/cifar10", "--num_steps",
                  total_steps=100, duration=1000)
        return sched.add_job(job)

    def test_late_init_gets_floor_not_sliver(self):
        from shockwave_tpu.sched.physical import INIT_LEASE_FLOOR_S
        sched = self._make_sched()
        try:
            job_id = self._add_job(sched)
            sched._current_round_start_time = (
                sched.get_current_timestamp() - 99.5)
            _, max_duration, _ = sched._init_job_callback(job_id)
            assert max_duration >= INIT_LEASE_FLOOR_S
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_floor_clamped_to_short_rounds(self):
        # With rounds shorter than the 45 s floor, an unclamped floor
        # would make every late init overrun its round and delay the
        # next round's dispatch on that chip.
        sched = self._make_sched(round_duration=30.0)
        try:
            job_id = self._add_job(sched)
            sched._current_round_start_time = (
                sched.get_current_timestamp() - 29.5)
            _, max_duration, _ = sched._init_job_callback(job_id)
            assert max_duration <= 30.0
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_gang_job_seeds_from_estimated_sf_row(self):
        """Physical scheduling of a multi-chip v5e job must start from
        the oracle's scale_factor>1 prior (measured sf=1 rate scaled by
        the reference's measured DP efficiency — scripts/profiling/
        extrapolate_sf.py), not the fabricated DEFAULT_THROUGHPUT."""
        from shockwave_tpu.core.oracle import read_throughputs
        from shockwave_tpu.sched.scheduler import DEFAULT_THROUGHPUT
        oracle_path = os.path.join(DATA, "v5e_throughputs.json")
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=oracle_path,
            config=SchedulerConfig(time_per_iteration=100.0),
            expected_num_workers=1, port=free_port())
        try:
            sched.register_worker("v5e", num_chips=4)
            job = Job(None, "Transformer (batch size 64)",
                      "python3 main.py --batch_size 64", "translation",
                      "--step", total_steps=1000, duration=1000,
                      scale_factor=4)
            job_id = sched.add_job(job)
            got = sched._throughputs[job_id]["v5e"]
            want = read_throughputs(oracle_path)["v5e"][
                ("Transformer (batch size 64)", 4)]["null"]
            assert got == want
            assert got != DEFAULT_THROUGHPUT
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_fresh_init_gets_remaining_round(self):
        sched = self._make_sched()
        try:
            job_id = self._add_job(sched)
            sched._current_round_start_time = sched.get_current_timestamp()
            _, max_duration, _ = sched._init_job_callback(job_id)
            assert 90.0 <= max_duration <= 100.0
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


@pytest.mark.runtime
class TestIteratorLogTimelines:
    def test_done_logs_reach_job_timeline(self):
        """Iterator logs shipped in Done RPCs must land in the job's
        event timeline (reference: scheduler.py:4341-4715)."""
        sched_port = free_port()
        worker_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0, max_rounds=3),
            expected_num_workers=1, port=sched_port)

        class LoggingStub(StubWorkerDaemon):
            def _run_job(self, jobs, worker_id, round_id):
                def execute():
                    for j in jobs:
                        it = IteratorToSchedulerClient(
                            j["job_id"], worker_id, "localhost",
                            self.sched_port)
                        it.init()
                    time.sleep(self.execution_time)
                    steps = [min(int(self.throughput * self.round_duration),
                                 j["num_steps"]) for j in jobs]
                    self._client.notify_done(
                        [j["job_id"] for j in jobs], worker_id, steps,
                        [self.execution_time] * len(jobs),
                        iterator_logs=["[PROGRESS] [STEPS] 5 synthetic"])
                threading.Thread(target=execute, daemon=True).start()

        worker = LoggingStub(sched_port, worker_port, num_chips=1,
                             throughput=100.0)
        try:
            sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=150, duration=10000))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 1
            timeline = sched._job_timelines.get(0, [])
            assert any("ITERATOR" in line and "[STEPS] 5" in line
                       for line in timeline), timeline
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)


#: jax's CPU backend cannot lower cross-process collectives on some
#: versions (XlaRuntimeError at the first process_allgather). The gang
#: tests gate on the subprocess's own error rather than a version probe:
#: the same test passes unchanged wherever the backend supports it
#: (gloo-enabled jax, TPU pods) and SKIPs — loudly, with the triage
#: pointer — where it cannot (EXPERIMENTS.md "Pre-existing tier-1
#: failures").
CPU_MULTIPROC_UNSUPPORTED = "Multiprocess computations aren't implemented"


def assert_gang_member_ok(proc, out):
    """Assert a gang member subprocess exited cleanly, skipping the test
    when the failure is the CPU backend's missing multi-process
    collective support (environment limitation, not a repo bug)."""
    if proc.returncode != 0 and CPU_MULTIPROC_UNSUPPORTED in out:
        pytest.skip("CPU backend lacks multi-process collectives in this "
                    "jax build; gang-barrier coverage needs a "
                    "gloo-enabled jax or a TPU pod")
    assert proc.returncode == 0, out[-3000:]


class TestGangBarrier:
    def test_two_process_gang_synchronized_exit(self, tmp_path):
        """Two gang members over jax.distributed: consensus-style leases
        from a stub scheduler, a cross-process collective every step, and
        a synchronized exit barrier before the gang checkpoint."""
        import subprocess
        import sys

        sched_port = free_port()
        coord_port = free_port()
        init_calls, update_calls = [], []

        def init_job(job_id):
            init_calls.append(job_id)
            return (6, 1e6, 0.0)

        def update_lease(job_id, worker_id, steps, duration, max_steps,
                         max_duration):
            update_calls.append((worker_id, steps))
            return (int(max_steps), float(max_duration), 0.0, 1e9)

        server = serve_scheduler(sched_port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": init_job,
            "UpdateLease": update_lease,
            "UpdateResourceRequirement": lambda *a: None,
        })
        procs = []
        try:
            for pid in (0, 1):
                from conftest import cpu_subprocess_env
                env = cpu_subprocess_env()
                env.update({
                    "SWTPU_JOB_ID": "0", "SWTPU_WORKER_ID": str(pid),
                    "SWTPU_ROUND_ID": "0",
                    "SWTPU_SCHED_ADDR": "localhost",
                    "SWTPU_SCHED_PORT": str(sched_port),
                    # One virtual device per process: the gang's global
                    # mesh is the 2 processes, not threads in one.
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                })
                procs.append(subprocess.Popen(
                    [sys.executable,
                     os.path.join(os.path.dirname(__file__),
                                  "gang_worker.py"),
                     "--coordinator", f"localhost:{coord_port}",
                     "--num_processes", "2", "--process_id", str(pid),
                     "--checkpoint_dir", str(tmp_path)],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, env=env))
            outs = []
            for proc in procs:
                out, _ = proc.communicate(timeout=120)
                outs.append(out)
            for proc, out in zip(procs, outs):
                assert_gang_member_ok(proc, out)
            for pid, out in enumerate(outs):
                assert f"EXITED process={pid} steps=6 barriers=1" in out, out
                # allgather of (x+1) over 2 procs summed: both saw the
                # same global values, proving the gang was coupled.
            assert len(init_calls) == 2  # both members init'd the lease
            for pid in (0, 1):
                with open(tmp_path / f"proc{pid}.ckpt") as f:
                    assert f.read() == "steps=6"
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            server.stop(grace=0)

    def test_duration_expiry_lands_on_same_step_despite_skew(self, tmp_path):
        """Time-based lease expiry must be step-deterministic across the
        gang even when members' local clocks/step rates differ: decisions
        fire only at shared K-step boundaries on an allreduce-agreed
        duration. A divergent exit would deadlock the per-step collective
        (and the reference's barrier-only design cannot prevent it)."""
        import re
        import subprocess
        import sys

        sched_port = free_port()
        coord_port = free_port()

        server = serve_scheduler(sched_port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": lambda job_id: (10**6, 1.0, 0.0),
            "UpdateLease": lambda job_id, worker_id, steps, duration,
                max_steps, max_duration: (int(max_steps),
                                          float(max_duration), 0.0, 1e9),
            "UpdateResourceRequirement": lambda *a: None,
        })
        procs = []
        try:
            for pid, skew in ((0, 0.0), (1, 6.0)):
                from conftest import cpu_subprocess_env
                env = cpu_subprocess_env()
                env.update({
                    "SWTPU_JOB_ID": "0", "SWTPU_WORKER_ID": str(pid),
                    "SWTPU_ROUND_ID": "0",
                    "SWTPU_SCHED_ADDR": "localhost",
                    "SWTPU_SCHED_PORT": str(sched_port),
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
                })
                procs.append(subprocess.Popen(
                    [sys.executable,
                     os.path.join(os.path.dirname(__file__),
                                  "gang_worker.py"),
                     "--coordinator", f"localhost:{coord_port}",
                     "--num_processes", "2", "--process_id", str(pid),
                     "--checkpoint_dir", str(tmp_path),
                     "--gang_sync_every", "4", "--skew_ms", str(skew)],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, env=env))
            steps_seen = []
            member_outs = []
            for proc in procs:
                out, _ = proc.communicate(timeout=180)
                member_outs.append(out)
            for proc, out in zip(procs, member_outs):
                assert_gang_member_ok(proc, out)
            for out in member_outs:
                m = re.search(r"EXITED process=\d steps=(\d+) barriers=1",
                              out)
                assert m, out[-2000:]
                steps_seen.append(int(m.group(1)))
            assert steps_seen[0] == steps_seen[1], steps_seen
            assert steps_seen[0] > 0
        finally:
            for proc in procs:
                if proc.poll() is None:
                    proc.kill()
            server.stop(grace=0)


@pytest.mark.slow
@pytest.mark.tpu
class TestAccordionEndToEnd:
    def test_real_subprocess_accordion_rescale(self, tmp_path):
        """Full physical-mode adaptation round trip with NO stubs, on
        the REAL chip: the real worker daemon (subprocess) dispatches
        the real cifar10 workload (sub-subprocess) in accordion mode;
        the monitor requests the big batch, UpdateResourceRequirement
        reaches the scheduler, the job is redispatched at the rescaled
        batch size, and completes. Real models are minutes-per-step on
        CPU, so this runs only where a TPU backend is reachable."""
        import subprocess
        import sys

        from conftest import REPO_ROOT, ambient_accelerator_env

        env = ambient_accelerator_env()
        try:
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.default_backend())"],
                capture_output=True, text=True, timeout=90, env=env)
        except subprocess.TimeoutExpired:
            pytest.skip("TPU backend unreachable (wedged tunnel?)")
        if probe.returncode != 0 or "tpu" not in probe.stdout:
            pytest.skip("no reachable TPU backend")

        sched_port = free_port()
        worker_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=45.0, max_rounds=12),
            expected_num_workers=1, port=sched_port)
        # 10-batch epochs: the accordion monitor decides once per epoch,
        # and dataset-sized epochs would take many rounds.
        env["SWTPU_SYNTH_EPOCH_BATCHES"] = "10"
        # Log to a file, not a PIPE: the worker (and the job grandchild
        # that inherits the fd) can emit more than the OS pipe buffer
        # over a 400 s run, and an undrained pipe would deadlock them.
        log_path = tmp_path / "worker.log"
        log_f = open(log_path, "w")
        worker = subprocess.Popen(
            [sys.executable, "-m", "shockwave_tpu.runtime.worker",
             "--worker_type", "v100", "--sched_addr", "127.0.0.1",
             "--sched_port", str(sched_port),
             "--worker_port", str(worker_port), "--num_chips", "1",
             "--data_dir", str(tmp_path / "nodata"),
             "--checkpoint_dir", str(tmp_path / "ckpt")],
            stdout=log_f, stderr=subprocess.STDOUT, text=True,
            cwd=REPO_ROOT, env=env)
        try:
            job = Job(None, "ResNet-18 (batch size 128)",
                      "python3 main.py --data_dir=%s/cifar10 "
                      "--batch_size 128",
                      "image_classification/cifar10", "--num_steps",
                      needs_data_dir=True,
                      total_steps=60, duration=10000, mode="accordion")
            job_id = sched.add_job(job)
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()
            deadline = time.time() + 400
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.5)
            assert len(sched._completed_jobs) == 1, "job did not complete"
        finally:
            sched._done_event.set()
            worker.terminate()
            try:
                worker.wait(timeout=30)
            except subprocess.TimeoutExpired:
                worker.kill()
                worker.wait(timeout=30)
            log_f.close()
            out = log_path.read_text()
            sched._server.stop(grace=0)
        # The redispatch after the resize must carry the doubled batch.
        assert "--batch_size 256" in out, out[-3000:]


class TestInflightTimeAccounting:
    """Physical-mode priorities must charge currently-running microtasks
    their elapsed time (reference: scheduler.py:3640-3666) — without it
    a lease-extended job reads as starved and sticky placement
    re-extends it until completion (the sequential-JCT failure the CPU
    loopback fidelity run exposed) — but must NOT phantom-charge
    microtasks whose process already exited this round."""

    def _sched(self):
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=60.0),
            expected_num_workers=1, port=free_port())
        sched.workers.id_to_type[0] = "v100"
        return sched

    def test_running_member_charged_elapsed(self):
        sched = self._sched()
        try:
            jid = JobIdPair(0, None)
            now = sched.get_current_timestamp()
            sched.rounds.current_assignments[jid] = (0,)
            sched.acct.latest_timestamps[jid] = now - 30.0
            sched._running_jobs.add(jid)
            job_t, worker_t = sched._inflight_elapsed_times(now)
            assert job_t[jid]["v100"] == pytest.approx(30.0, abs=1.0)
            assert worker_t["v100"] == pytest.approx(30.0, abs=1.0)
        finally:
            sched._server.stop(grace=0)

    def test_exited_member_not_charged(self):
        sched = self._sched()
        try:
            jid = JobIdPair(0, None)
            now = sched.get_current_timestamp()
            sched.rounds.current_assignments[jid] = (0,)
            sched.acct.latest_timestamps[jid] = now - 30.0
            # Done callback already removed it from _running_jobs and
            # charged its real time; the idle tail must not be added.
            job_t, worker_t = sched._inflight_elapsed_times(now)
            assert job_t == {} and worker_t == {}
        finally:
            sched._server.stop(grace=0)

    def test_elapsed_clamped_to_last_reset(self):
        sched = self._sched()
        try:
            jid = JobIdPair(0, None)
            now = sched.get_current_timestamp()
            sched.rounds.current_assignments[jid] = (0,)
            sched.acct.latest_timestamps[jid] = now - 500.0
            sched._running_jobs.add(jid)
            sched._last_reset_time = now - 20.0
            job_t, _ = sched._inflight_elapsed_times(now)
            # Time before the allocation reset was already folded into
            # the deficits; only post-reset time counts.
            assert job_t[jid]["v100"] == pytest.approx(20.0, abs=1.0)
        finally:
            sched._server.stop(grace=0)


# ---------------------------------------------------------------------------
# Fault tolerance: RPC resilience layer, fault injection, worker liveness
# ---------------------------------------------------------------------------

import collections
import json
import random
import signal
import subprocess
import sys

import grpc

from shockwave_tpu.runtime import faults
from shockwave_tpu.runtime import resilience
from shockwave_tpu.runtime.clients import SchedulerToWorkerClient as _S2W
from shockwave_tpu.runtime.resilience import (CircuitBreaker,
                                              CircuitOpenError, RetryPolicy,
                                              RpcUnavailableError,
                                              call_with_retry)

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture
def fault_injector():
    inj = faults.get_injector()
    inj.clear()
    yield inj
    inj.clear()


class _FakeRpcError(grpc.RpcError):
    def __init__(self, code):
        self._code = code

    def code(self):
        return self._code


class TestResilienceLayer:
    """Unit tests for the retry/deadline/circuit-breaker primitives."""

    def test_retries_transport_errors_then_succeeds(self):
        calls, sleeps = [], []

        def flaky(request, timeout=None):
            calls.append(timeout)
            if len(calls) < 3:
                raise _FakeRpcError(grpc.StatusCode.UNAVAILABLE)
            return "ok"

        out = call_with_retry(
            flaky, None, method="t",
            policy=RetryPolicy(deadline_s=1.0, total_budget_s=100.0,
                               max_attempts=5),
            sleep=sleeps.append, rng=random.Random(7))
        assert out == "ok"
        assert len(calls) == 3
        # Full jitter: each sleep is uniform in (0, bounded-exponential]
        # — bounded above by the deterministic schedule, floored at 1%
        # of it so retries never fire same-instant.
        bounds = [0.25, 0.5]
        assert len(sleeps) == 2
        for got, bound in zip(sleeps, bounds):
            assert 0.01 * bound <= got <= bound
        assert all(t is not None and t <= 1.0 for t in calls)  # deadlines

    def test_backoff_jitter_is_seed_deterministic(self):
        """Satellite: jittered backoff must be reproducible under a
        seeded RNG (chaos drills assert retry timing), and the ceiling
        must match the legacy deterministic schedule."""
        policy = RetryPolicy(deadline_s=1.0, total_budget_s=100.0,
                            max_attempts=6)

        def draws(seed):
            rng = random.Random(seed)
            return [policy.backoff(a, rng) for a in range(5)]

        assert draws(42) == draws(42)  # same seed, same schedule
        assert draws(42) != draws(43)  # jitter is real
        for attempt, value in enumerate(draws(42)):
            bound = policy.backoff_bound(attempt)
            assert 0.01 * bound <= value <= bound
        # No RNG: the deterministic ceiling (legacy exact-bound tests).
        assert [policy.backoff(a) for a in range(3)] == [0.25, 0.5, 1.0]
        # Process-wide RNG is seedable for end-to-end drills.
        resilience.seed_backoff_jitter(5)
        a = policy.backoff(2, resilience._jitter_rng)
        resilience.seed_backoff_jitter(5)
        assert policy.backoff(2, resilience._jitter_rng) == a

    def test_budget_exhaustion_raises_unavailable(self):
        def dead(request, timeout=None):
            raise _FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)

        with pytest.raises(RpcUnavailableError) as exc:
            call_with_retry(
                dead, None, method="t",
                policy=RetryPolicy(deadline_s=0.5, total_budget_s=10.0,
                                   max_attempts=3),
                sleep=lambda s: None)
        assert exc.value.attempts == 3
        assert exc.value.last_code == grpc.StatusCode.DEADLINE_EXCEEDED

    def test_non_retryable_code_propagates_unchanged(self):
        calls = []

        def wrong(request, timeout=None):
            calls.append(1)
            raise _FakeRpcError(grpc.StatusCode.INVALID_ARGUMENT)

        with pytest.raises(grpc.RpcError):
            call_with_retry(wrong, None, method="t", policy=RetryPolicy(),
                            sleep=lambda s: None)
        assert len(calls) == 1  # peer answered: no retry

    def test_narrowed_retryable_codes(self):
        """Done-style calls retry UNAVAILABLE only: a deadline expiry may
        mean the server is still processing attempt 1."""
        def slow(request, timeout=None):
            raise _FakeRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)

        with pytest.raises(grpc.RpcError) as exc:
            call_with_retry(
                slow, None, method="t", policy=RetryPolicy(max_attempts=5),
                retryable=frozenset({grpc.StatusCode.UNAVAILABLE}),
                sleep=lambda s: None)
        assert not isinstance(exc.value, RpcUnavailableError)

    def test_circuit_opens_half_opens_and_recloses(self):
        clock = [0.0]
        br = CircuitBreaker(failure_threshold=2, reset_timeout_s=5.0,
                            clock=lambda: clock[0])
        assert br.state == "closed"
        br.record_failure()
        assert br.allow()
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()  # fails fast while open
        clock[0] = 6.0
        assert br.state == "half-open"
        assert br.allow()       # one probe admitted
        assert not br.allow()   # ...but only one
        br.record_success()
        assert br.state == "closed"

    def test_open_circuit_fails_fast_without_calling(self):
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=100.0)
        br.record_failure()
        calls = []
        with pytest.raises(CircuitOpenError):
            call_with_retry(lambda r, timeout=None: calls.append(1), None,
                            method="t", policy=RetryPolicy(), breaker=br)
        assert calls == []

    def test_half_open_probe_failure_reopens(self):
        clock = [0.0]
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                            clock=lambda: clock[0])
        br.record_failure()
        clock[0] = 6.0
        assert br.allow()
        br.record_failure()  # probe failed: reopen from now
        assert br.state == "open"
        clock[0] = 10.0
        assert not br.allow()
        clock[0] = 12.0
        assert br.allow()


class TestFaultInjectorUnit:
    def test_after_and_times_windows(self, fault_injector):
        fault_injector.install([dict(method="Done", action="drop",
                                     after=1, times=2)])
        rule = fault_injector._rules[0]
        fired = [rule.should_fire() for _ in range(5)]
        assert fired == [False, True, True, False, False]

    def test_method_matching(self):
        rule = faults.FaultRule(method="Done")
        assert rule.matches("shockwave_tpu.WorkerToScheduler/Done")
        assert rule.matches("Done")
        assert not rule.matches("RunJob")
        assert faults.FaultRule(method="*").matches("anything")

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            faults.FaultRule(method="Done", action="explode")


@pytest.mark.faults
@pytest.mark.timeout(60)
class TestRpcDeadlines:
    """Acceptance: no scheduler-side RPC can block indefinitely — a
    blackholed method returns within the configured budget."""

    def test_blackholed_run_job_returns_within_budget(self, fault_injector):
        port = free_port()
        server = serve_worker(port, {
            "RunJob": lambda jobs, wid, rid: None,
            "KillJob": lambda j: None, "Reset": lambda: None,
            "Shutdown": lambda: None,
        })
        # Hold RunJob for 2 s server-side; the client's deadline is 0.3 s.
        fault_injector.install([dict(method="RunJob", action="blackhole",
                                     delay_s=2.0)])
        client = _S2W("localhost", port,
                      policy=RetryPolicy(deadline_s=0.3, total_budget_s=1.2,
                                         max_attempts=2))
        try:
            start = time.monotonic()
            with pytest.raises(RpcUnavailableError):
                client.run_job([dict(job_id=1, command="x",
                                     working_directory="", needs_data_dir=False,
                                     num_steps_arg="--steps", num_steps=1,
                                     mode="static")], worker_id=0, round_id=0)
            elapsed = time.monotonic() - start
            # 2 attempts x 0.3 s deadline + 0.25 s backoff, plus slack —
            # nowhere near the 2 s server-side hold per attempt.
            assert elapsed < 1.9, elapsed
        finally:
            fault_injector.clear()
            client.close()
            server.stop(grace=0)
            time.sleep(2.2)  # let blackholed handler threads drain

    def test_dropped_rpc_is_retried_to_success(self, fault_injector):
        port = free_port()
        received = []
        server = serve_worker(port, {
            "RunJob": lambda jobs, wid, rid: received.append(wid),
            "KillJob": lambda j: None, "Reset": lambda: None,
            "Shutdown": lambda: None,
        })
        fault_injector.install([dict(method="RunJob", action="drop",
                                     times=1)])
        client = _S2W("localhost", port,
                      policy=RetryPolicy(deadline_s=2.0, total_budget_s=10.0,
                                         max_attempts=3,
                                         backoff_base_s=0.05))
        try:
            client.run_job([dict(job_id=1, command="x", working_directory="",
                                 needs_data_dir=False, num_steps_arg="--s",
                                 num_steps=1, mode="static")],
                           worker_id=7, round_id=0)
            assert received == [7]
            assert ("shockwave_tpu.SchedulerToWorker/RunJob", "drop") in \
                fault_injector.fired
        finally:
            client.close()
            server.stop(grace=0)

    def test_ping_probe_round_trip(self):
        port = free_port()
        server = serve_worker(port, {
            "RunJob": lambda jobs, wid, rid: None,
            "KillJob": lambda j: None, "Reset": lambda: None,
            "Shutdown": lambda: None,
        })
        client = _S2W("localhost", port)
        try:
            client.ping(deadline_s=2.0)  # no exception = alive
        finally:
            client.close()
            server.stop(grace=0)

    def test_ping_dead_endpoint_fails_within_deadline(self):
        client = _S2W("localhost", free_port())
        start = time.monotonic()
        with pytest.raises(RpcUnavailableError):
            client.ping(deadline_s=0.3)
        assert time.monotonic() - start < 2.0
        client.close()


@pytest.mark.faults
@pytest.mark.timeout(120)
class TestWorkerDeathMidRound:
    """Acceptance: SIGKILL one of two (real-process) workers mid-round —
    the scheduler detects the loss via the heartbeat monitor, requeues
    the job, completes the round, and the requeued job's completion
    lands in makespan accounting. Deterministic: the victim worker is
    frozen via its --freeze_after_round hook BEFORE the SIGKILL, so
    nothing races the kill signal."""

    def _spawn_stub(self, sched_port, tmp_path, name, freeze_after=None):
        from conftest import REPO_ROOT
        state = tmp_path / f"{name}.json"
        log = open(tmp_path / f"{name}.log", "w")
        cmd = [sys.executable, os.path.join(TESTS_DIR, "fault_stub_worker.py"),
               "--sched_port", str(sched_port),
               "--worker_port", str(free_port()),
               "--num_chips", "1", "--state_file", str(state)]
        if freeze_after is not None:
            cmd += ["--freeze_after_round", str(freeze_after)]
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                                env=env)
        return proc, state, log

    def test_sigkilled_worker_job_requeued_and_completes(self, tmp_path):
        sched_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=2.0,
                heartbeat_interval_s=0.2, worker_timeout_s=0.6,
                worker_probe_deadline_s=0.3, worker_probe_failures=1,
                kill_wait_s=0.5, kill_heartbeat_freshness_s=0.5,
                job_completion_buffer_s=5.0),
            expected_num_workers=2, port=sched_port)
        survivor_p, _, log_a = self._spawn_stub(sched_port, tmp_path, "a")
        victim_p, victim_state, log_b = self._spawn_stub(
            sched_port, tmp_path, "b", freeze_after=0)
        try:
            deadline = time.time() + 20
            while time.time() < deadline and not victim_state.exists():
                time.sleep(0.05)
            victim_ids = set(json.loads(victim_state.read_text())["worker_ids"])

            # Two 300-step jobs: each needs two 200-step-capacity rounds,
            # so both are live when round 1 starts and the victim freezes.
            for _ in range(2):
                sched.add_job(Job(
                    None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=300, duration=10000))
            threading.Thread(target=sched.run, daemon=True).start()

            # Wait until the victim has swallowed (frozen) a round-1
            # dispatch, then SIGKILL it mid-round.
            frozen_log = tmp_path / "b.log"
            deadline = time.time() + 20
            while time.time() < deadline:
                if frozen_log.exists() and "FROZEN" in frozen_log.read_text():
                    break
                time.sleep(0.05)
            else:
                pytest.fail("victim never received its round-1 dispatch")
            os.kill(victim_p.pid, signal.SIGKILL)
            kill_time = time.time()

            # Detection: chips retired within timeout + probe + slack.
            deadline = time.time() + 10
            while time.time() < deadline:
                if victim_ids <= sched.workers.dead:
                    break
                time.sleep(0.05)
            assert victim_ids <= sched.workers.dead, "worker loss undetected"
            detect_latency = time.time() - kill_time
            assert detect_latency < 3.0, detect_latency

            # Both jobs complete on the survivor; the requeued one's
            # completion is accounted.
            deadline = time.time() + 30
            while time.time() < deadline:
                if len(sched._completed_jobs) == 2:
                    break
                time.sleep(0.1)
            assert len(sched._completed_jobs) == 2, (
                f"jobs stuck: completed={sched._completed_jobs}")
            for int_id in (0, 1):
                assert sched.acct.completion_times[JobIdPair(int_id)] is not None
            assert sched.get_last_completion_time() > 0
            # Surviving capacity only.
            assert sum(sched.workers.cluster_spec.values()) == 1
        finally:
            sched._done_event.set()
            for proc in (survivor_p, victim_p):
                if proc.poll() is None:
                    proc.kill()
                proc.wait(timeout=10)
            log_a.close()
            log_b.close()
            sched._server.stop(grace=0)


@pytest.mark.faults
@pytest.mark.timeout(120)
class TestDoneBlackholeSynthesis:
    """Satellite: the Done report is blackholed (dropped through the
    worker's whole retry budget); the round watchdog synthesizes a
    failed micro-task, the round completes, and the requeued job
    finishes once the fault window closes."""

    def test_done_dropped_then_job_requeued(self, fault_injector):
        sched_port = free_port()
        worker_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=2.0,
                heartbeat_interval_s=0,  # worker is alive; isolate Done
                kill_wait_s=0.3, kill_heartbeat_freshness_s=0.3,
                job_completion_buffer_s=0.5),
            expected_num_workers=1, port=sched_port)

        class QuietStub(StubWorkerDaemon):
            def _run_job(self, jobs, worker_id, round_id):
                def execute():
                    try:
                        for j in jobs:
                            it = IteratorToSchedulerClient(
                                j["job_id"], worker_id, "localhost",
                                self.sched_port)
                            max_steps, _, _ = it.init()
                        time.sleep(self.execution_time)
                        steps = [min(int(self.throughput * self.round_duration),
                                     j["num_steps"], int(max_steps))
                                 for j in jobs]
                        self._client.notify_done(
                            [j["job_id"] for j in jobs], worker_id, steps,
                            [self.execution_time] * len(jobs))
                    except Exception:  # noqa: BLE001 - injected fault
                        pass
                threading.Thread(target=execute, daemon=True).start()

        # The worker's Done policy retries 4 times; swallow exactly one
        # full report (4 server-side hits), then heal.
        fault_injector.install([dict(method="Done", action="drop", times=4)])
        worker = QuietStub(sched_port, worker_port, num_chips=1,
                           throughput=100.0)
        try:
            sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=150, duration=10000))
            threading.Thread(target=sched.run, daemon=True).start()
            deadline = time.time() + 40
            while time.time() < deadline:
                if len(sched._completed_jobs) == 1:
                    break
                time.sleep(0.1)
            assert len(sched._completed_jobs) == 1, "job never completed"
            drops = [f for f in fault_injector.fired if f[1] == "drop"]
            assert len(drops) >= 4, drops  # the whole retry budget was eaten
            assert sched.acct.completion_times[JobIdPair(0)] is not None
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)


@pytest.mark.runtime
class TestWorkerRejoinIdempotent:
    """A daemon re-registering from a known endpoint gets its ORIGINAL
    chip ids back (idempotent RegisterWorker), whether it was declared
    dead first or re-registered while still considered live (slow
    restart / duplicated register retry)."""

    def _make_sched(self):
        return PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=100.0,
                                   heartbeat_interval_s=0),
            expected_num_workers=2, port=free_port())

    def test_rejoin_after_death_revives_same_ids(self):
        sched = self._make_sched()
        try:
            ids, _ = sched._register_worker_rpc("v5e", 2, "127.0.0.1", 7001)
            assert sched.workers.cluster_spec["v5e"] == 2
            with sched._cv:
                sched._retire_worker_host(("127.0.0.1", 7001))
            assert sched.workers.cluster_spec["v5e"] == 0
            assert set(ids) <= sched.workers.dead
            ids2, _ = sched._register_worker_rpc("v5e", 2, "127.0.0.1", 7001)
            assert ids2 == ids
            assert sched.workers.cluster_spec["v5e"] == 2
            assert not (set(ids) & sched.workers.dead)
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_reregister_while_live_is_idempotent(self):
        sched = self._make_sched()
        try:
            ids, _ = sched._register_worker_rpc("v5e", 2, "127.0.0.1", 7002)
            ids2, _ = sched._register_worker_rpc("v5e", 2, "127.0.0.1", 7002)
            assert ids2 == ids
            assert sched.workers.cluster_spec["v5e"] == 2  # no ghost chips
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_dead_worker_requeues_in_round_job(self):
        """Retiring a host whose chip runs a job marks the job failed-in-
        round (zero-step synthesized done) without charging the job a
        failure, and prunes dead chips from the next round's plan."""
        sched = self._make_sched()
        try:
            ids, _ = sched._register_worker_rpc("v5e", 1, "127.0.0.1", 7003)
            job_id = sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=100, duration=1000))
            with sched._cv:
                sched.rounds.current_assignments[job_id] = tuple(ids)
                sched.rounds.next_assignments = collections.OrderedDict(
                    {job_id: tuple(ids)})
                sched._retire_worker_host(("127.0.0.1", 7003))
            assert job_id in sched.rounds.completed_in_round  # round rolls
            assert job_id in sched.acct.jobs                  # requeued
            assert sched.acct.failures[job_id] == 0           # not job's fault
            assert job_id not in sched.rounds.next_assignments
            tl = sched._job_timelines[job_id.integer_job_id()]
            assert any("WORKER_FAILED" in line for line in tl), tl
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


@pytest.mark.runtime
class TestKillRearmCap:
    """Satellite: the heartbeat-freshness kill deferral is capped per
    dispatch, so a job that keeps renewing its lease but never honors
    expiry is killed after max_kill_rearms re-arms and the round
    regains liveness."""

    def _make_sched(self, max_rearms):
        return PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=100.0,
                                   heartbeat_interval_s=0,
                                   kill_heartbeat_freshness_s=30.0,
                                   max_kill_rearms=max_rearms,
                                   kill_wait_s=0.1),
            expected_num_workers=1, port=free_port())

    def test_perpetually_fresh_job_killed_after_cap(self):
        sched = self._make_sched(2)
        try:
            job_id = sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=100, duration=1000))
            sched.rounds.current_assignments[job_id] = (0,)
            sched._ever_signaled.add(job_id)

            class _StubClient:
                addr, port = "127.0.0.1", 0
                killed = []

                def kill_job(self, int_id):
                    self.killed.append(int_id)

            sched._worker_connections[0] = _StubClient()
            done = []
            sched.done_callback = lambda *a: done.append(a)

            # kill_wait_s=0.1 in the config keeps the real _cv.wait in
            # the kill path short — no wait stubbing (which would turn
            # the allocation thread's waits into a lock-holding spin).
            for attempt in range(3):
                # The job heartbeats right before every kill check —
                # the pathological always-fresh renewer.
                sched._last_heartbeat[job_id] = sched.get_current_timestamp()
                sched._kill_job(job_id)
                timer = sched._completion_events.pop(job_id, None)
                if timer is not None:
                    timer.cancel()
                if _StubClient.killed:
                    break
            # Two deferrals allowed, third check kills.
            assert attempt == 2, attempt
            assert _StubClient.killed == [job_id.integer_job_id()]
            assert done, "zero-step done must be synthesized"
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_rearm_counter_cleared_on_dispatch(self):
        sched = self._make_sched(2)
        try:
            job_id = sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=100, duration=1000))
            sched._kill_rearm_counts[job_id] = 2

            class _NullClient:
                addr, port = "127.0.0.1", 1

                def run_job(self, *a):
                    pass

            sched._worker_connections[0] = _NullClient()
            with sched._cv:
                sched._try_dispatch_job(job_id, (0,))
            assert job_id not in sched._kill_rearm_counts
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


@pytest.mark.timeout(60)
class TestDispatcherEscalation:
    """Satellite: after the group leader exits on SIGTERM, surviving
    group members (forked helpers that ignore SIGTERM) are probed and
    SIGKILLed so the chip cannot stay wedged."""

    def test_sigterm_ignoring_helper_is_killed(self, tmp_path):
        from shockwave_tpu.runtime.dispatcher import Dispatcher
        pid_file = tmp_path / "grandchild.pid"
        leader_code = (
            "import os, subprocess, sys, time\n"
            "child = subprocess.Popen([sys.executable, '-c', "
            "'import signal, time; "
            "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
            "time.sleep(120)'])\n"
            f"open({str(pid_file)!r}, 'w').write(str(child.pid))\n"
            "time.sleep(120)\n")
        proc = subprocess.Popen([sys.executable, "-c", leader_code],
                                start_new_session=True)
        d = Dispatcher(round_duration=1.0, chip_ids=[0],
                       worker_rpc_client=None, sched_addr="127.0.0.1",
                       sched_port=1234, run_dirs={}, data_dir=None,
                       checkpoint_dir=str(tmp_path))
        try:
            deadline = time.time() + 15
            while time.time() < deadline and not pid_file.exists():
                time.sleep(0.05)
            grandchild = int(pid_file.read_text())
            d._processes[7] = proc
            d.kill_job(7, grace_s=0.5)
            # Leader dies on SIGTERM; the escalation thread must then
            # probe the group and SIGKILL the TERM-ignoring grandchild.
            deadline = time.time() + 15
            while time.time() < deadline:
                try:
                    os.kill(grandchild, 0)
                except ProcessLookupError:
                    break
                time.sleep(0.1)
            else:
                pytest.fail("grandchild survived: chip would stay wedged")
        finally:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            if pid_file.exists():
                try:
                    os.kill(int(pid_file.read_text()), signal.SIGKILL)
                except (ProcessLookupError, ValueError):
                    pass


class TestSolverBudgetCapCoercion:
    """Satellite: solver_budget_cap_rounds must be coerced with a clear
    config error — not a bare TypeError out of the clamp comparison."""

    def _config(self, cap, pipelined=True):
        sw = {"num_gpus": 2, "solver_budget_cap_rounds": cap}
        return SchedulerConfig(time_per_iteration=10.0, shockwave=sw,
                               pipelined_planning=pipelined)

    def test_null_means_mode_default(self):
        from shockwave_tpu.sched.scheduler import Scheduler
        # Pipelined physical (default): full-budget default, 2.0 rounds.
        sched = Scheduler(get_policy("shockwave"), simulate=False,
                          config=self._config(None))
        assert sched._shockwave_planner.opts.budget_cap_rounds == 2.0
        # Inline physical: the historical half-round default.
        sched = Scheduler(get_policy("shockwave"), simulate=False,
                          config=self._config(None, pipelined=False))
        assert sched._shockwave_planner.opts.budget_cap_rounds == 0.5

    def test_numeric_string_is_coerced(self):
        from shockwave_tpu.sched.scheduler import Scheduler
        sched = Scheduler(get_policy("shockwave"), simulate=False,
                          config=self._config("0.25"))
        assert sched._shockwave_planner.opts.budget_cap_rounds == 0.25

    def test_garbage_raises_descriptive_error(self):
        from shockwave_tpu.sched.scheduler import Scheduler
        with pytest.raises(ValueError, match="solver_budget_cap_rounds"):
            Scheduler(get_policy("shockwave"), simulate=False,
                      config=self._config("half a round"))

    def test_overlarge_cap_clamped_only_without_pipelining(self):
        from shockwave_tpu.sched.scheduler import Scheduler
        # Inline solve blocks the round loop -> clamp stands.
        sched = Scheduler(get_policy("shockwave"), simulate=False,
                          config=self._config(2.0, pipelined=False))
        assert sched._shockwave_planner.opts.budget_cap_rounds == 0.5
        # Pipelined solve runs off the round loop -> config cap honored.
        sched = Scheduler(get_policy("shockwave"), simulate=False,
                          config=self._config(2.0))
        assert sched._shockwave_planner.opts.budget_cap_rounds == 2.0


class TestCheckpointAheadReconcile:
    """A job whose restored checkpoint already satisfies its full budget
    (previous worker died post-checkpoint, pre-report) must report the
    scheduler's granted remainder — closing the accounting gap — rather
    than (0, 0), the micro-task-failure signal."""

    def test_reports_granted_remainder(self, tmp_path, monkeypatch):
        port = free_port()
        server = serve_scheduler(port, {
            "RegisterWorker": lambda **kw: ([0], 60.0),
            "Done": lambda *a: None,
            "InitJob": lambda job_id: (50, 1e6, 0.0),  # scheduler's remaining
            "UpdateLease": lambda *a: (50, 1e6, 0.0, 1e9),
            "UpdateResourceRequirement": lambda *a: None,
        })
        monkeypatch.setenv("SWTPU_JOB_ID", "2")
        monkeypatch.setenv("SWTPU_WORKER_ID", "0")
        monkeypatch.setenv("SWTPU_ROUND_ID", "5")
        monkeypatch.setenv("SWTPU_SCHED_ADDR", "localhost")
        monkeypatch.setenv("SWTPU_SCHED_PORT", str(port))
        try:
            from shockwave_tpu.runtime.iterator import LeaseIterator
            it = LeaseIterator(
                data_loader=list(range(10)), checkpoint_dir=str(tmp_path),
                load_checkpoint_func=lambda p: None,
                save_checkpoint_func=lambda p, s: None,
                synthetic_data=True, write_on_close=False)
            it.report_checkpoint_ahead()
            assert it.done
            it.complete()  # flushes PROGRESS lines (write_on_close=False)
            log = (tmp_path / ".swtpu" / "round=5" /
                   "worker=0.log").read_text()
            assert "[STEPS] 50" in log, log
            # The dispatcher scrapes the LAST progress values; the final
            # duration must be strictly positive ((0 steps, 0 s) is the
            # failure signal).
            last_duration = [line for line in log.splitlines()
                             if "[DURATION]" in line][-1]
            assert float(last_duration.rsplit(" ", 1)[-1]) > 0, log
        finally:
            server.stop(grace=0)


class TestDoneDuplicateGuard:
    """gRPC can return UNAVAILABLE after the server processed the call,
    so an at-least-once Done retry may double-deliver; one report per
    (job, worker) per dispatch is accepted."""

    def _make_sched(self):
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=100.0,
                                   heartbeat_interval_s=0),
            expected_num_workers=1, port=free_port())
        sched.register_worker("v5e", num_chips=1)
        return sched

    def _add_job(self, sched, total_steps=1000):
        return sched.add_job(Job(
            None, "ResNet-18 (batch size 32)",
            "python3 main.py --batch_size 32",
            "image_classification/cifar10", "--num_steps",
            total_steps=total_steps, duration=100000))

    def test_duplicate_report_counted_once(self):
        sched = self._make_sched()
        try:
            job_id = self._add_job(sched)
            with sched._cv:
                sched.rounds.current_assignments[job_id] = (0,)
                sched._running_jobs.add(job_id)  # normally set by InitJob
                sched._dispatch_stamp[(job_id, 0)] = (
                    sched.get_current_timestamp())
            sched.done_callback(job_id, 0, [50], [1.0])
            assert sched.acct.total_steps_run[job_id] == 50
            # The retry of the same report must be rejected at entry —
            # not parked at the boundary wait (which would hang here).
            with sched._cv:
                sched._running_jobs.add(job_id)
            sched.done_callback(job_id, 0, [50], [1.0])
            assert sched.acct.total_steps_run[job_id] == 50
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_fresh_dispatch_reaccepts(self):
        sched = self._make_sched()
        try:
            job_id = self._add_job(sched)
            with sched._cv:
                sched.rounds.current_assignments[job_id] = (0,)
                sched._running_jobs.add(job_id)
                sched._dispatch_stamp[(job_id, 0)] = (
                    sched.get_current_timestamp())
            sched.done_callback(job_id, 0, [50], [1.0])
            # Round rolls and the job is re-dispatched to the same chip.
            with sched._cv:
                sched.rounds.completed_in_round.clear()
                sched._running_jobs.add(job_id)
                sched._dispatch_stamp[(job_id, 0)] = (
                    sched.get_current_timestamp() + 0.001)
            sched.done_callback(job_id, 0, [60], [1.0])
            assert sched.acct.total_steps_run[job_id] == 110
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)

    def test_worker_death_never_drops_job_at_failure_threshold(self):
        """A job sitting one genuine failure below MAX_FAILED_ATTEMPTS
        must survive a worker crash: the synthesized zero-step done's
        +1 is pre-compensated, not restored after the fact (a post-hoc
        restore would miss a job the +1 already removed)."""
        from shockwave_tpu.sched.scheduler import MAX_FAILED_ATTEMPTS
        sched = self._make_sched()
        try:
            ids, _ = sched._register_worker_rpc("v5e", 1, "127.0.0.1", 7009)
            job_id = self._add_job(sched)
            with sched._cv:
                sched.acct.failures[job_id] = MAX_FAILED_ATTEMPTS - 1
                sched.rounds.current_assignments[job_id] = tuple(ids)
                sched._dispatch_stamp[(job_id, ids[0])] = (
                    sched.get_current_timestamp())
                sched._retire_worker_host(("127.0.0.1", 7009))
            assert job_id in sched.acct.jobs, "worker crash dropped the job"
            assert sched.acct.failures[job_id] == MAX_FAILED_ATTEMPTS - 1
            assert job_id in sched.rounds.completed_in_round
        finally:
            sched._done_event.set()
            sched._server.stop(grace=0)


class TestFaultChokepointFiltering:
    def test_freeze_hook_does_not_consume_rpc_rules(self, fault_injector):
        fault_injector.install([dict(method="*", action="drop", times=1)])
        # The dispatch hook can only freeze: it must not burn the one
        # firing slot of a drop rule (or log a phantom fired entry).
        assert not fault_injector.should_freeze("dispatch")
        assert fault_injector.fired == []
        rule = fault_injector._rules[0]
        assert rule.should_fire()  # slot still live for an RPC hook

    def test_rpc_hook_does_not_consume_freeze_rules(self, fault_injector):
        fault_injector.install([dict(method="*", action="freeze", times=1)])
        fault_injector.fire("shockwave_tpu.WorkerToScheduler/Done")
        assert fault_injector.fired == []
        assert fault_injector.should_freeze("dispatch")


@pytest.mark.faults
@pytest.mark.timeout(60)
class TestPartitionHealRevive:
    """A transient partition retires a healthy daemon that will never
    re-register (it registers once, at startup); the monitor must keep
    probing retired hosts and revive them when the partition heals."""

    def test_retired_host_revived_on_successful_probe(self):
        sched_port = free_port()
        worker_port = free_port()
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=100.0,
                heartbeat_interval_s=0.2, worker_timeout_s=0.4,
                worker_probe_deadline_s=0.3, worker_probe_failures=1),
            expected_num_workers=1, port=sched_port)
        worker = None
        try:
            # Register a worker endpoint with NO server behind it yet:
            # the monitor's probes fail and retire it (the "partition").
            ids, _ = sched._register_worker_rpc(
                "v5e", 1, "localhost", worker_port)
            deadline = time.time() + 10
            while time.time() < deadline and not (
                    set(ids) <= sched.workers.dead):
                time.sleep(0.05)
            assert set(ids) <= sched.workers.dead, "host never retired"

            # Partition heals: a server appears at the SAME endpoint.
            worker = serve_worker(worker_port, {
                "RunJob": lambda jobs, wid, rid: None,
                "KillJob": lambda j: None, "Reset": lambda: None,
                "Shutdown": lambda: None,
            })
            deadline = time.time() + 15
            while time.time() < deadline and (set(ids) & sched.workers.dead):
                time.sleep(0.05)
            assert not (set(ids) & sched.workers.dead), "host never revived"
            assert sched.workers.cluster_spec["v5e"] == 1
        finally:
            sched._done_event.set()
            if worker is not None:
                worker.stop(grace=0)
            sched._server.stop(grace=0)
