"""Online what-if control plane: digital-twin forks of the live
scheduler rolled forward in-memory (README "What-if control plane").

- fork.py — the one fork primitive (capture / thaw / rollforward /
  load_twin), reusing the journal snapshot serializer.
- plane.py — WhatIfPlane: Monte-Carlo admission control, knob
  auto-tuning, forecasts, shadow chaos.
- knobs.py — the tunable-knob surface (autoscaler headroom, solver
  budget, quarantine backoff).
"""
from . import fork, knobs
from .plane import WhatIfConfig, WhatIfPlane

__all__ = ["fork", "knobs", "WhatIfConfig", "WhatIfPlane"]
