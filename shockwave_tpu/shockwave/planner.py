"""Shockwave planner: owns job metadata, solve cadence, and round schedules.

Wraps the EG MILP (milp.py) with: uniform-share finish-time estimation,
schedule caching between re-solves, and work-conserving backfill of idle
chips (reference: scheduler/shockwave.py:20-285).
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional

from ..obs import names as obs_names
from .metadata import JobMetadata
from .milp import MilpOptions, plan_schedule

logger = logging.getLogger("shockwave_tpu.shockwave")


class ShockwavePlanner:
    def __init__(self, ngpus: int, future_nrounds: int, round_duration: float,
                 opts: Optional[MilpOptions] = None):
        assert ngpus > 0 and future_nrounds > 0 and round_duration > 0
        self.ngpus = ngpus
        self.future_nrounds = future_nrounds
        self.round_duration = round_duration
        self.opts = opts or MilpOptions()

        self.metadata: "OrderedDict[int, JobMetadata]" = OrderedDict()
        self.completed: "OrderedDict[int, JobMetadata]" = OrderedDict()
        self.schedules: "OrderedDict[int, List[int]]" = OrderedDict()
        self.round_ptr = 0
        self._resolve = True
        self._reestimate_share = True
        self.share_series: Dict[int, list] = {}
        # Per-solve quality telemetry (milp.SolveStats), appended by
        # every plan_schedule call; drivers persist it so scale runs
        # can prove the fallback chain stays cold.
        self.solve_stats: list = []
        # Durability hook: callable(event_type, data_dict) wired by the
        # scheduler when a write-ahead journal is attached, so progress
        # marks, waiting delays, round advances and solve outcomes are
        # journaled at their source and replay rebuilds the planner's
        # estimate state exactly. None = no journaling.
        self.journal = None
        # Observability handle, wired by the owning scheduler so spans
        # ride its injected clock (virtual in simulation). None falls
        # back to the process-global wall-clock bundle.
        self.obs = None

    def _journal_event(self, etype: str, data: dict) -> None:
        if self.journal is not None:
            self.journal(etype, data)

    def _obs_handle(self):
        if self.obs is None:
            from ..obs import get_observability
            return get_observability()
        return self.obs

    # The simulator checkpoints pickle the whole planner; the obs
    # handle's clock and the journal hook are bound methods of the
    # owning scheduler, so neither may ride along (each would drag a
    # ghost scheduler copy into the pickle). The resume path
    # (Scheduler._load_simulation_checkpoint) re-wires both.
    def __getstate__(self):
        state = dict(self.__dict__)
        state["obs"] = None
        state["journal"] = None
        return state

    @classmethod
    def from_config(cls, config: dict) -> "ShockwavePlanner":
        opts = MilpOptions(
            rel_gap=config.get("solver_rel_gap", 1e-3),
            timeout=config.get("solver_timeout", 15),
            rhomax=config.get("rhomax", 1.0),
            k=config.get("k", 1e-3),
            lam=config.get("lambda", 12.0),
            logapx_bases=tuple(config.get(
                "log_approximation_bases", (0.0, 0.2, 0.4, 0.6, 0.8, 1.0))),
            budget_cap_rounds=config.get("solver_budget_cap_rounds", 0.5),
        )
        return cls(
            ngpus=config["num_gpus"],
            future_nrounds=config.get("future_rounds", 20),
            round_duration=config["time_per_iteration"],
            opts=opts,
        )

    # -- job lifecycle -----------------------------------------------------

    def add_job(self, job_id: int, meta: JobMetadata) -> None:
        assert job_id not in self.metadata
        self.metadata[job_id] = meta
        self.request_resolve()
        self._reestimate_share = True

    def remove_job(self, job_id: int) -> None:
        assert job_id in self.metadata and job_id not in self.completed
        self.completed[job_id] = self.metadata.pop(job_id)
        self.request_resolve()
        self._reestimate_share = True

    def mark_progress(self, job_id: int, epoch_progress: int) -> None:
        meta = self.metadata.get(job_id) or self.completed.get(job_id)
        if meta is None:
            return
        meta.set_epoch_progress(min(epoch_progress, meta.epochs))
        meta.reset_waiting_delay()
        self._journal_event("planner_progress",
                            {"int_id": job_id, "epoch": epoch_progress})

    def add_waiting_delay(self, job_id: int, delay: float) -> None:
        if job_id in self.metadata:
            self.metadata[job_id].add_waiting_delay(delay)
            self._journal_event("planner_waiting",
                                {"int_id": job_id, "delay": delay})

    def increment_round(self) -> None:
        self.round_ptr += 1
        self._journal_event("planner_round", {})

    def request_resolve(self) -> None:
        self._resolve = True

    # -- share estimation --------------------------------------------------

    def _estimate_uniform_share_finish_times(self) -> None:
        """Record each job's finish-time estimate under a uniform 1/n share;
        the momentumed average of these is the FTF target
        (reference: shockwave.py:88-120)."""
        if not self._reestimate_share:
            return
        njobs = len(self.metadata)
        with self._obs_handle().span(obs_names.SPAN_ESTIMATE_REFRESH,
                                     njobs=njobs, round=self.round_ptr):
            for job_id, job in self.metadata.items():
                share = min(1.0, self.ngpus / njobs)
                job.calibrate_profiled_epoch_duration()
                estimate = job.timestamp_submit + (
                    sum(job.epoch_duration[:job.epoch_progress])
                    + job.dirichlet_posterior_remaining_runtime(
                        job.epoch_progress)
                ) / share
                self.share_series.setdefault(job_id, []).append(
                    (self.round_ptr, estimate))
        self._reestimate_share = False

    # -- scheduling --------------------------------------------------------

    def round_schedule(self) -> List[int]:
        """Job ids to run this round, re-solving the MILP if requested."""
        if not self._resolve and self.round_ptr in self.schedules:
            return self.schedules[self.round_ptr]

        job_ids = list(self.metadata.keys())
        jobs = list(self.metadata.values())
        if not jobs:
            return []

        self._estimate_uniform_share_finish_times()
        share_series = [self.share_series[j] for j in job_ids]

        obs = self._obs_handle()
        with obs.span(obs_names.SPAN_PLANNER_SOLVE, njobs=len(jobs),
                      round=self.round_ptr):
            x = plan_schedule(jobs, self.round_ptr, self.future_nrounds,
                              self.round_duration, self.ngpus, share_series,
                              self.opts, stats_out=self.solve_stats)
        if self.solve_stats:
            from dataclasses import asdict
            stats = self.solve_stats[-1]
            # The MILP's own wall time is already measured inside
            # plan_schedule (SolveStats.wall_s, journaled with the
            # outcome) — observe that rather than re-timing, so replay
            # and live runs histogram the same number.
            obs.observe(obs_names.MILP_SOLVE_SECONDS, stats.wall_s,
                        path=stats.path)
            if stats.path != "ftf":
                obs.inc(obs_names.SOLVER_FALLBACKS_TOTAL, path=stats.path)
            self._journal_event("solve_outcome", asdict(stats))
        self.schedules = self._construct_schedules(x, job_ids, jobs)
        self._resolve = False
        return self.schedules[self.round_ptr]

    def _construct_schedules(self, x, job_ids, jobs) -> "OrderedDict[int, List[int]]":
        """Solution matrix -> per-round job lists, with work-conserving
        backfill of idle chips by longest remaining runtime
        (reference: shockwave.py:213-285)."""
        schedules: "OrderedDict[int, List[int]]" = OrderedDict()
        for r in range(self.future_nrounds):
            round_index = self.round_ptr + r
            selected = [job_ids[j] for j in range(len(job_ids)) if x[j, r]]
            if not selected:
                logger.warning("no jobs scheduled in round %d", round_index)
            used = sum(self.metadata[j].nworkers for j in selected)
            idle = self.ngpus - used
            if idle > 0:
                others = [j for j in range(len(job_ids))
                          if job_ids[j] not in selected]
                others.sort(key=lambda j: jobs[j].dirichlet_posterior_remaining_runtime(),
                            reverse=True)
                for j in others:
                    if jobs[j].nworkers <= idle:
                        idle -= jobs[j].nworkers
                        selected.append(job_ids[j])
                    if idle <= 0:
                        break
            schedules[round_index] = selected
        return schedules
