"""gRPC clients for all three control-plane directions
(reference: runtime/rpc/{scheduler_client,worker_client,iterator_client}.py).

Every call carries a deadline and rides the resilience layer
(`resilience.py`): bounded exponential-backoff retry on transport
failures, and — for the scheduler->worker direction — a circuit breaker
per worker channel so one dead worker fails fast instead of costing
every round a full retry budget. No call in this module can block
indefinitely.

Control-plane HA (``SWTPU_HA_ENDPOINT_FILE`` / `endpoint_file`): the
worker->scheduler clients can re-resolve the scheduler endpoint from
the leader lease file across a failover. On a transport failure (or a
fenced ex-leader's FAILED_PRECONDITION), the report is held in the
calling thread and retried against freshly-resolved endpoints for the
failover budget; the per-scheduler circuit breaker fails the dead-
leader window fast and is RESET whenever the endpoint or leader epoch
changes, so the new leader never inherits an open circuit from the
dead one's era. Duplicate delivery stays impossible: the promoted
leader's recovery cleared its dispatch stamps, so a replayed pre-
failover report is rejected by the existing orphan/dedup gates.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import grpc

from .proto import control_pb2 as pb
from .resilience import (EPOCH_METADATA_KEY, CircuitBreaker, RetryPolicy,
                         RpcUnavailableError, call_with_retry,
                         policy_from_env)
from .rpc import Stub

logger = logging.getLogger("shockwave_tpu.runtime")

#: Poll cadence of the worker-side failover retry loop.
FAILOVER_RETRY_INTERVAL_S = 0.25


def _ha_endpoint_file(explicit: Optional[str]) -> Optional[str]:
    if explicit is not None:
        return explicit or None
    return os.environ.get("SWTPU_HA_ENDPOINT_FILE") or None


def _read_endpoint(path: str) -> Optional[Tuple[str, int, int]]:
    """(addr, port, epoch) from a leader lease file, or None when the
    file is absent/unparseable (pre-first-lease bring-up)."""
    try:
        with open(path) as f:
            lease = json.load(f)
        return (str(lease["addr"]), int(lease["port"]),
                int(lease.get("epoch", 0)))
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _read_lease_budget(path: str) -> Optional[float]:
    """The leader-advertised failover_budget_s from the lease file (the
    --ha config's worker-side half arrives through the lease, not the
    environment), or None when absent."""
    try:
        with open(path) as f:
            budget = json.load(f).get("failover_budget_s")
        return None if budget is None else float(budget)
    except (OSError, ValueError, TypeError):
        return None


def _is_fenced_leader_error(error: Exception) -> bool:
    """A FAILED_PRECONDITION from a fenced ex-leader (or a fence
    rejection): the peer is alive but no longer the leader — re-resolve
    instead of retrying the same endpoint."""
    return (isinstance(error, grpc.RpcError)
            and error.code() == grpc.StatusCode.FAILED_PRECONDITION)

#: Scheduler -> worker: short deadlines — the scheduler holds its round
#: lock across dispatch, so a dead worker must surface fast.
WORKER_RPC_POLICY = RetryPolicy(deadline_s=10.0, total_budget_s=25.0,
                                max_attempts=3)
#: Worker/iterator -> scheduler: more patient (the scheduler may be
#: solving a MILP), but still bounded.
SCHED_RPC_POLICY = RetryPolicy(deadline_s=30.0, total_budget_s=90.0,
                               max_attempts=4)


class SchedulerToWorkerClient:
    """Scheduler -> one worker daemon."""

    def __init__(self, addr: str, port: int,
                 policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 epoch_source: Optional[Callable[[], Optional[int]]] = None):
        self.addr = addr
        self.port = port
        self._policy = policy or WORKER_RPC_POLICY
        self.breaker = breaker or CircuitBreaker()
        # Control-plane HA: a callable yielding this scheduler's fenced
        # leader epoch; attached as RPC metadata so workers can reject
        # a deposed leader's dispatches. None = HA disabled, no
        # metadata (workers pass everything unfenced).
        self._epoch_source = epoch_source
        self._channel = grpc.insecure_channel(f"{addr}:{port}")
        self._stub = Stub(self._channel, "shockwave_tpu.SchedulerToWorker")

    def _epoch_metadata(self):
        if self._epoch_source is not None:
            epoch = self._epoch_source()
            if epoch is not None:
                return ((EPOCH_METADATA_KEY, str(int(epoch))),)
        return None

    def _call(self, method: str, request, policy: Optional[RetryPolicy] = None,
              metadata_extra: Optional[tuple] = None):
        metadata = self._epoch_metadata()
        if metadata_extra:
            metadata = tuple(metadata or ()) + tuple(metadata_extra)
        return call_with_retry(
            getattr(self._stub, method), request,
            method=f"worker {self.addr}:{self.port}/{method}",
            policy=policy or self._policy, breaker=self.breaker,
            metadata=metadata)

    def run_job(self, job_descriptions: Sequence[dict], worker_id: int,
                round_id: int,
                metadata_extra: Optional[tuple] = None) -> None:
        """`metadata_extra` carries the fleet-trace span context
        (obs/propagation.rpc_metadata) beside the HA epoch — the same
        gRPC-metadata channel, empty when tracing is off."""
        request = pb.RunJobRequest(
            jobs=[pb.JobDescription(**d) for d in job_descriptions],
            worker_id=worker_id, round_id=round_id)
        self._call("RunJob", request, metadata_extra=metadata_extra)

    def kill_job(self, job_id: int, deadline_s: Optional[float] = None) -> None:
        """With `deadline_s`, a single bounded attempt — for best-effort
        kills issued under the scheduler lock, where the full retry
        budget would stall the round pipeline."""
        policy = None
        if deadline_s is not None:
            from dataclasses import replace
            policy = replace(self._policy.one_shot(), deadline_s=deadline_s,
                             total_budget_s=deadline_s)
        self._call("KillJob", pb.KillJobRequest(job_id=job_id), policy=policy)

    def reset(self) -> None:
        self._call("Reset", pb.Empty())

    def ping(self, deadline_s: Optional[float] = None) -> None:
        """Single-attempt liveness probe; raises RpcUnavailableError (or
        CircuitOpenError) on failure. The heartbeat monitor owns the
        retry cadence, so no client-side retries here."""
        policy = self._policy.one_shot()
        if deadline_s is not None:
            from dataclasses import replace
            policy = replace(policy, deadline_s=deadline_s,
                             total_budget_s=deadline_s)
        self._call("Ping", pb.Empty(), policy=policy)

    def shutdown(self) -> None:
        # Carries the epoch like every other dispatch-effecting RPC: a
        # deposed leader's parting Shutdown must NOT take the successor's
        # fleet down with it (the worker fence rejects stale epochs).
        try:
            self._stub.Shutdown(pb.Empty(), timeout=5,
                                metadata=self._epoch_metadata())
        except grpc.RpcError:
            pass  # worker may exit before replying

    def close(self) -> None:
        self._channel.close()


class WorkerToSchedulerClient:
    """Worker daemon -> scheduler.

    With an HA endpoint file (explicit or $SWTPU_HA_ENDPOINT_FILE),
    the client re-resolves the scheduler address from the leader lease
    whenever a call fails, carries a per-scheduler-channel circuit
    breaker so the dead-leader window fails fast, and retries held
    reports against the new leader for `failover_budget_s` — the
    "buffered and retried across the failover window" contract."""

    #: Endpoint re-resolution state (race-detector verdict, documented):
    #: `_connect`/`refresh_endpoint` rebind these as atomic reference
    #: swaps from whichever dispatch/report thread first observes the
    #: failover; a concurrent RPC that grabbed the OLD stub fails with
    #: UNAVAILABLE on the closed channel and re-enters through the
    #: resilience retry loop, which re-reads the fresh endpoint — the
    #: failure mode IS the designed failover path. `_done_policy` is
    #: rebound once at registration, before dispatch traffic exists.
    _EXTERNALLY_SYNCHRONIZED = frozenset({
        "_sched_addr", "_sched_port", "_channel", "_stub",
        "_done_policy", "_epoch",
    })

    def __init__(self, sched_addr: str, sched_port: int,
                 policy: Optional[RetryPolicy] = None,
                 endpoint_file: Optional[str] = None,
                 failover_budget_s: Optional[float] = None):
        self._policy = policy or policy_from_env(SCHED_RPC_POLICY)
        self._done_policy = self._policy
        self._endpoint_file = _ha_endpoint_file(endpoint_file)
        # Failover-budget precedence: explicit constructor arg >
        # leader-advertised lease value (read per call — the lease is
        # the --ha config's delivery channel to workers) >
        # $SWTPU_HA_FAILOVER_BUDGET_S > 30s.
        self._explicit_budget_s = failover_budget_s
        try:
            self._default_budget_s = float(os.environ.get(
                "SWTPU_HA_FAILOVER_BUDGET_S", "30"))
        except ValueError:
            self._default_budget_s = 30.0
        # The breaker only exists for the failover story: without HA,
        # adding one would change long-standing single-leader retry
        # timing the fault suite pins.
        self.breaker: Optional[CircuitBreaker] = (
            CircuitBreaker() if self._endpoint_file else None)
        self._endpoint_lock = threading.Lock()
        self._epoch = 0
        if self._endpoint_file is not None:
            # Seed the epoch cursor from the current lease so the first
            # refresh_endpoint() is a no-op while the leader that
            # spawned us is still it.
            resolved = _read_endpoint(self._endpoint_file)
            if resolved is not None and resolved[:2] == (sched_addr,
                                                         int(sched_port)):
                self._epoch = resolved[2]
        self._connect(sched_addr, sched_port)

    def _connect(self, addr: str, port: int) -> None:
        self._sched_addr = addr
        self._sched_port = int(port)
        self._channel = grpc.insecure_channel(f"{addr}:{port}")
        self._stub = Stub(self._channel, "shockwave_tpu.WorkerToScheduler")

    def refresh_endpoint(self) -> bool:
        """Re-resolve the scheduler endpoint from the leader lease.
        Returns True when the endpoint or leader epoch changed — the
        channel is rebuilt and the breaker RESET (an open circuit is
        evidence about the DEAD leader, not the new one)."""
        if self._endpoint_file is None:
            return False
        resolved = _read_endpoint(self._endpoint_file)
        if resolved is None:
            return False
        addr, port, epoch = resolved
        with self._endpoint_lock:
            changed = ((addr, port) != (self._sched_addr, self._sched_port)
                       or epoch > self._epoch)
            if not changed:
                return False
            logger.warning(
                "scheduler endpoint re-resolved: %s:%d (epoch %d) -> "
                "%s:%d (epoch %d); resetting channel%s",
                self._sched_addr, self._sched_port, self._epoch,
                addr, port, epoch,
                " + breaker" if self.breaker is not None else "")
            old = self._channel
            self._connect(addr, port)
            self._epoch = epoch
            if self.breaker is not None:
                self.breaker.reset()
        try:
            old.close()
        except Exception:  # noqa: BLE001 - best-effort channel cleanup
            logger.debug("closing replaced scheduler channel failed",
                         exc_info=True)
        return True

    def failover_budget_s(self) -> float:
        """How long reports are held across a failover window — the
        leader's lease advertises it (HAConfig.failover_budget_s)."""
        if self._explicit_budget_s is not None:
            return self._explicit_budget_s
        if self._endpoint_file is not None:
            lease_budget = _read_lease_budget(self._endpoint_file)
            if lease_budget is not None:
                return lease_budget
        return self._default_budget_s

    def _call_with_failover(self, do_call, label: str):
        """Run one report RPC, holding it across a failover window:
        on transport failure / open circuit / fenced ex-leader, keep
        re-resolving the endpoint and retrying until the budget runs
        out. Without an endpoint file this is a single attempt (the
        historical behavior)."""
        deadline = time.monotonic() + self.failover_budget_s()
        while True:
            try:
                return do_call()
            except (RpcUnavailableError, grpc.RpcError) as e:
                fenced = _is_fenced_leader_error(e)
                if not (isinstance(e, RpcUnavailableError) or fenced):
                    raise  # the peer answered; its verdict stands
                if (self._endpoint_file is None
                        or time.monotonic() >= deadline):
                    raise
                logger.warning(
                    "%s failed (%s); holding the report and re-resolving "
                    "the scheduler endpoint", label,
                    "fenced leader" if fenced else e)
                time.sleep(FAILOVER_RETRY_INTERVAL_S)
                self.refresh_endpoint()

    def stretch_done_deadline(self, min_deadline_s: float) -> None:
        """Raise Done's deadline floor. The scheduler's Done handler
        legitimately blocks an early finisher until the round boundary,
        so the deadline must cover a full round — the daemon calls this
        once the round duration is known (at registration)."""
        from dataclasses import replace
        if min_deadline_s > self._done_policy.deadline_s:
            self._done_policy = replace(
                self._done_policy, deadline_s=min_deadline_s,
                total_budget_s=max(self._done_policy.total_budget_s,
                                   min_deadline_s * 1.5))

    def register_worker(self, worker_type: str, ip_addr: str, port: int,
                        num_chips: int) -> Tuple[List[int], float]:
        # Single attempt with a deadline: the daemon's bring-up loop owns
        # registration retries (with its own, much longer window).
        response = self._stub.RegisterWorker(pb.RegisterWorkerRequest(
            worker_type=worker_type, ip_addr=ip_addr, port=port,
            num_chips=num_chips), timeout=self._policy.deadline_s)
        if not response.success:
            raise RuntimeError(response.error_message)
        return list(response.worker_ids), response.round_duration

    def notify_done(self, job_ids: Sequence[int], worker_id: int,
                    num_steps: Sequence[int], execution_times: Sequence[float],
                    iterator_logs: Optional[Sequence[str]] = None) -> None:
        # Done is not idempotent (the scheduler aggregates each report
        # into step accounting), so only connection-level failures are
        # retried: a deadline expiry may mean the server is still
        # processing attempt 1, and replaying would double-count.
        # Across an HA failover the report is held and redelivered to
        # the promoted leader — safe even when the dead leader DID
        # process it first, because promotion clears the dispatch
        # stamps and the orphan gate discards the replay.
        request = pb.DoneRequest(
            job_ids=list(job_ids), worker_id=worker_id,
            num_steps=[int(s) for s in num_steps],
            execution_times=list(execution_times),
            iterator_logs=list(iterator_logs or []))
        self._call_with_failover(
            lambda: call_with_retry(
                self._stub.Done, request,
                method="scheduler/Done", policy=self._done_policy,
                breaker=self.breaker,
                retryable=frozenset({grpc.StatusCode.UNAVAILABLE})),
            label=f"Done report for jobs {list(job_ids)}")


class IteratorToSchedulerClient:
    """Training process (lease iterator) -> scheduler. A fresh channel per
    call keeps the client robust to scheduler restarts, as in the reference;
    deadlines + bounded retry keep a dead scheduler from hanging the
    training process inside a lease renewal. With $SWTPU_HA_ENDPOINT_FILE
    set (the dispatcher exports the environment into training processes),
    each call resolves the CURRENT leader from the lease file, so a lease
    renewal lands on the promoted standby without any process restart."""

    def __init__(self, job_id: int, worker_id: int, sched_addr: str,
                 sched_port: int, policy: Optional[RetryPolicy] = None,
                 endpoint_file: Optional[str] = None):
        self._job_id = job_id
        self._worker_id = worker_id
        self._static_target = f"{sched_addr}:{sched_port}"
        self._endpoint_file = _ha_endpoint_file(endpoint_file)
        self._policy = policy or policy_from_env(SCHED_RPC_POLICY)

    def _target(self) -> str:
        if self._endpoint_file is not None:
            resolved = _read_endpoint(self._endpoint_file)
            if resolved is not None:
                return f"{resolved[0]}:{resolved[1]}"
        return self._static_target

    def _stub(self, channel):
        return Stub(channel, "shockwave_tpu.IteratorToScheduler")

    def _call(self, method: str, request):
        with grpc.insecure_channel(self._target()) as channel:
            return call_with_retry(
                getattr(self._stub(channel), method), request,
                method=f"scheduler/{method}", policy=self._policy)

    def init(self) -> Tuple[int, float, float]:
        r = self._call("InitJob", pb.InitJobRequest(
            job_id=self._job_id, worker_id=self._worker_id))
        return r.max_steps, r.max_duration, r.extra_time

    def update_lease(self, steps: int, duration: float, max_steps: int,
                     max_duration: float,
                     measured_reports: Optional[Sequence[str]] = None
                     ) -> Tuple[int, float, float, float]:
        """`measured_reports` piggybacks serving sketch deltas
        (serving/measured.py wire lines) on the renewal heartbeat —
        the per-round telemetry channel for replicas whose extended
        lease means Done only fires at drain."""
        r = self._call("UpdateLease", pb.UpdateLeaseRequest(
            job_id=self._job_id, worker_id=self._worker_id,
            steps=int(steps), duration=duration, max_steps=int(max_steps),
            max_duration=max_duration,
            measured_reports=list(measured_reports or [])))
        return r.max_steps, r.max_duration, r.run_time_so_far, r.deadline

    def update_resource_requirement(self, big_bs: bool, small_bs: bool) -> None:
        self._call("UpdateResourceRequirement",
                   pb.UpdateResourceRequirementRequest(
                       job_id=self._job_id, worker_id=self._worker_id,
                       big_bs=big_bs, small_bs=small_bs))
