"""The online what-if control plane: a scheduler that simulates itself.

`WhatIfPlane` hangs off one scheduler (simulated or physical) and turns
the PR 7 fast sim core into a live decision aid, all on the single fork
primitive in fork.py:

- **Monte-Carlo admission control** (``gate_admission``): at every
  trace admission (training job or serving-service registration), fork
  K seeded twins with and without the candidate, roll the horizon, and
  admit/defer on an FTF-unfairness + serving-SLO envelope. The default
  mode is ``always_admit`` — the gate never rolls a twin and the
  canonical replays stay bit-identical.
- **Knob auto-tuning** (``tune_knob``): every ``tune_interval_rounds``,
  sweep one live knob (knobs.py) across candidate values on twin
  rollouts and commit the winner; the sweep evidence is journaled as
  the ``whatif_knob`` event, so a resumed scheduler re-applies the
  chosen value.
- **Forecasts** (``forecast_interval_rounds``): p50/p99 projected
  drain-time and serving-attainment quantiles from K seeded rollouts,
  exported as gauges and surfaced on /healthz.
- **Shadow chaos** (``shadow_chaos``): each forecast cycle also rolls
  one twin under a seeded injected fault (the PR 8 chaos action set)
  and checks the zero-failure-charge invariant — a low-rate continuous
  validator against the digital twin instead of the live cluster.

Everything the plane decides is recorded in ``decision_log`` (drivers
persist it into byte-reproducible artifacts) and is derived only from
scheduler state + seeded RNG — no wall clocks, so identical runs make
identical decisions (the determinism analyzer pass covers this
package).
"""
from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..obs import names as obs_names
from . import fork
from .knobs import get_knob

#: Projected-rho cap: an active job with zero exclusive-duration data
#: (or a stalled rollout) must not produce inf/nan in artifacts.
RHO_CAP = 100.0


@dataclass
class WhatIfConfig:
    """Plane knobs (SchedulerConfig.whatif block; unknown keys refuse
    loudly, same contract as the serving/health configs)."""

    #: Base seed of every twin-reseeding draw.
    seed: int = 0
    # ---- admission control ----
    #: "always_admit" (default: never rolls a twin, bit-identical
    #: replays) or "gate" (roll with/without the candidate and defer
    #: over the envelope below).
    admission: str = "always_admit"
    admission_horizon_rounds: int = 12
    #: Seeded rollout samples per decision leg (the Monte-Carlo width).
    admission_samples: int = 2
    #: Defer when the with-candidate worst projected rho exceeds this...
    admission_rho_limit: float = 1.10
    #: ...AND beats the without-candidate worst by at least this margin.
    admission_min_gain: float = 0.02
    #: Serving floor: defer when admitting drops projected horizon
    #: attainment below this while deferring keeps it at or above.
    admission_slo_floor: float = 0.999
    #: Deferral granularity (rounds of the live round duration).
    admission_defer_rounds: float = 2.0
    #: A candidate deferred this many times is admitted regardless —
    #: admission control trades queueing delay, never starvation.
    admission_max_defers: int = 8
    #: Candidate-slack guard: a candidate is only deferrable while its
    #: accumulated wait (including the prospective deferral) stays
    #: under this fraction of its fair-share budget (exclusive x
    #: contention). Deferral wait counts INSIDE the deferred job's own
    #: JCT/rho (the scheduler admits it at its ORIGINAL arrival), so
    #: the gate must pick victims whose rho barely moves — large jobs —
    #: rather than laundering small jobs' wait into the tail it is
    #: trying to cut.
    admission_wait_budget: float = 0.35
    #: Fast path: admit without a rollout while requested chips
    #: (active + candidate) stay at or under load_guard * cluster.
    admission_load_guard: float = 1.0
    # ---- knob auto-tuning ----
    tune_knob: Optional[str] = None
    tune_interval_rounds: int = 25
    tune_horizon_rounds: int = 12
    tune_samples: int = 1
    #: Candidate grid override (default: the knob's own grid).
    tune_candidates: Optional[Sequence[float]] = None
    # ---- forecasts + shadow chaos ----
    forecast_interval_rounds: int = 0
    forecast_horizon_rounds: int = 15
    forecast_samples: int = 3
    shadow_chaos: bool = False
    # ---- validation/test hook ----
    #: Capture a detached (blob, queued, remaining) triple at this round
    #: boundary (fork-fidelity tests and the chaos twin validator).
    capture_at_round: Optional[int] = None

    @classmethod
    def from_dict(cls, config: Optional[dict]) -> "WhatIfConfig":
        if not config:
            return cls()
        config = {k: v for k, v in config.items()
                  if not k.startswith("_")}  # _comment keys, config-file
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"unknown what-if option(s): {sorted(unknown)}")
        cfg = cls(**config)
        if cfg.admission not in ("always_admit", "gate"):
            raise ValueError("whatif.admission must be 'always_admit' "
                             f"or 'gate', got {cfg.admission!r}")
        return cfg


@dataclass
class RolloutScore:
    """One twin rollout, scored. Pure numbers (artifact-safe)."""

    worst_rho: float
    attainment: float
    progress_steps: int
    projected_drain_s: Optional[float]
    completed: int

    def as_dict(self) -> dict:
        return {"worst_rho": round(self.worst_rho, 6),
                "attainment": round(self.attainment, 6),
                "progress_steps": int(self.progress_steps),
                "projected_drain_s": (None if self.projected_drain_s is None
                                      else round(self.projected_drain_s, 2)),
                "completed": int(self.completed)}


class WhatIfPlane:
    """One scheduler's what-if plane. Simulation drives it through the
    event loop's hooks; the physical scheduler captures under its lock
    and rolls on a background thread (sched/physical.py)."""

    #: Decision/telemetry state shared between the physical what-if
    #: thread (rollouts append their verdicts), the round pipeline
    #: (capture bookkeeping under the scheduler lock) and the obs
    #: exporter's request thread (status() inside /healthz). Guarded by
    #: the plane's own leaf lock — surfaced by the race-detector pass:
    #: forecast/shadow appends ran OFF the scheduler lock while
    #: status() iterated the same lists.
    _LOCK_PROTECTED = frozenset({
        "decision_log", "knob_log", "forecast_log", "shadow_log",
        "max_fork_s", "forks", "rollouts", "captured",
        "_defer_counts", "_last_tune_round", "_last_forecast_round",
    })

    def __init__(self, sched, config: Optional[dict] = None):
        import threading

        from ..analysis.sanitizer import maybe_wrap
        self._sched = sched
        self.cfg = WhatIfConfig.from_dict(config)
        # Leaf lock (never held across a rollout or another subsystem's
        # lock): protects the _LOCK_PROTECTED registry above.
        self._lock = maybe_wrap(threading.Lock(), "WhatIfPlane._lock")
        self.decision_log: List[dict] = []
        self.knob_log: List[dict] = []
        self.forecast_log: List[dict] = []
        self.shadow_log: List[dict] = []
        self.max_fork_s = 0.0
        self.forks = 0
        self.rollouts = 0
        #: capture_at_round output: (blob, queued_copy, remaining_jobs).
        self.captured: Optional[Tuple[bytes, list, int]] = None
        self._defer_counts: dict = {}
        self._last_tune_round = -(10 ** 9)
        self._last_forecast_round = -(10 ** 9)

    # The plane never rides into snapshots/checkpoints (the scheduler
    # excludes it, like _obs); nothing to __getstate__.

    # ------------------------------------------------------------------
    # Fork plumbing
    # ------------------------------------------------------------------

    def _capture(self) -> bytes:
        import time as _time  # fork wall cost is telemetry, not state
        t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
        blob = fork.capture(self._sched)
        elapsed = _time.monotonic() - t0  # swtpu-check: ignore[determinism]
        with self._lock:
            self.max_fork_s = max(self.max_fork_s, elapsed)
            self.forks += 1
        return blob

    def _roll(self, blob: bytes, *, seed: Optional[int], purpose: str,
              horizon: int, add_job=None, knob=None, knob_value=None,
              fault_events=None,
              cf: Optional[float] = None) -> RolloutScore:
        sched = self._sched
        twin = fork.thaw(sched, blob, seed=seed)
        if knob is not None:
            knob.set(twin, knob_value)
        now0 = twin.get_current_timestamp()
        steps0 = self._training_steps(twin)
        completed0 = len(twin._completed_jobs)
        serving0 = self._serving_totals(twin)
        if add_job is not None:
            # Detached candidate copy: the twin's add_job mutates it.
            twin.add_job(pickle.loads(pickle.dumps(add_job)),
                         timestamp=now0)
        fork.rollforward(twin, horizon_rounds=horizon,
                         fault_events=fault_events)
        with self._lock:
            self.rollouts += 1
        sched.obs.inc(obs_names.WHATIF_ROLLOUTS_TOTAL, purpose=purpose)
        return self._score(twin, now0, steps0, completed0, serving0,
                           cf=cf)

    @staticmethod
    def _training_steps(twin) -> int:
        """Total training steps across ALL jobs ever admitted —
        total_steps_run entries survive job completion, so a job
        finishing mid-horizon keeps contributing to the progress/rate
        deltas instead of making them go negative."""
        return sum(steps for j, steps in twin.acct.total_steps_run.items()
                   if j not in twin._serving_job_ids)

    @staticmethod
    def _serving_totals(twin) -> Tuple[float, float]:
        if twin._serving_tier is None:
            return (0.0, 0.0)
        offered = sum(s.requests_offered
                      for s in twin._serving_tier.services.values())
        ok = sum(s.requests_ok
                 for s in twin._serving_tier.services.values())
        return (offered, ok)

    def _score(self, twin, now0: float, steps0: int, completed0: int,
               serving0: Tuple[float, float],
               cf: Optional[float] = None) -> RolloutScore:
        now1 = twin.get_current_timestamp()
        # Worst-case FTF over the horizon: completed jobs by their real
        # rho, still-active jobs by elapsed-so-far against their
        # exclusive budget (a lower bound that catches starvation).
        # `cf` pins one contention reference across a decision's
        # with/without legs — each twin's own trace count differs by
        # exactly the candidate, which would otherwise bias the
        # comparison toward admitting.
        from ..sched import simcore
        num_chips = len(twin.workers.worker_ids)
        if cf is None:
            cf = (max(1.0, twin._num_jobs_in_trace / num_chips)
                  if num_chips else 1.0)
        worst = 0.0
        if twin._profiles:
            for j, ct in twin.acct.completion_times.items():
                if ct is None or j in twin._serving_job_ids:
                    continue
                profile = twin._profile_for(j.integer_job_id())
                if profile is None:
                    continue  # serving lines carry no epoch profile
                exclusive = sum(profile["duration_every_epoch"])
                if exclusive > 0:
                    worst = max(worst, ct / (exclusive * cf))
        worst = max(worst, simcore.projected_unfairness(twin, now1,
                                                        cf=cf))
        worst = min(worst, RHO_CAP)
        # Serving attainment over the horizon window only.
        offered1, ok1 = self._serving_totals(twin)
        d_offered = offered1 - serving0[0]
        attainment = ((ok1 - serving0[1]) / d_offered
                      if d_offered > 0 else 1.0)
        active = [j for j in twin.acct.jobs
                  if j not in twin._serving_job_ids]
        steps1 = self._training_steps(twin)
        remaining = sum(twin._get_remaining_steps(j) for j in active)
        progress = max(steps1 - steps0, 0)
        elapsed = now1 - now0
        rate = (steps1 - steps0) / elapsed if elapsed > 0 else 0.0
        if not active:
            projected = twin._last_completion_time or now1
        elif rate > 0:
            projected = now1 + remaining / rate
        else:
            projected = None
        return RolloutScore(
            worst_rho=worst, attainment=attainment,
            progress_steps=progress,
            projected_drain_s=projected,
            completed=len(twin._completed_jobs) - completed0)

    def _seed(self, *parts: int) -> int:
        out = self.cfg.seed & 0x7FFFFFFF
        for p in parts:
            out = (out * 1_000_003 + int(p)) & 0x7FFFFFFF
        return out

    # ------------------------------------------------------------------
    # Monte-Carlo admission control (simulation event-loop hook)
    # ------------------------------------------------------------------

    def gate_admission(self, job, arrival_time: float, queued) -> float:
        """Verdict for one candidate admission. Returns deferral
        seconds (0.0 = admit now). Called from the simulator's arrival
        loop; the heap is empty there, so the fork point is a clean
        round boundary."""
        sched = self._sched
        cfg = self.cfg
        if cfg.admission != "gate":
            return 0.0
        key = id(job)
        now = sched.get_current_timestamp()
        with self._lock:
            defers = self._defer_counts.get(key, 0)
        if defers >= cfg.admission_max_defers:
            self._log_admission(job, now, "admit", defers,
                                reason="max_defers")
            return 0.0
        defer_s = cfg.admission_defer_rounds * sched._time_per_iteration
        if not self._within_wait_budget(job, arrival_time, now, defer_s):
            # Candidate-slack guard: another deferral would spend more
            # of this job's own fair-share budget on waiting than the
            # envelope could ever win back.
            self._log_admission(job, now, "admit", defers,
                                reason="wait_budget")
            return 0.0
        # Fast path: plenty of room — admit without paying a rollout.
        chips = sum(sched.workers.cluster_spec.values())
        demand = job.scale_factor + sum(
            sched.acct.jobs[j].scale_factor for j in sched.acct.jobs
            if j not in sched._serving_job_ids)
        demand += sched._serving_tier.reserved_total() \
            if sched._serving_tier is not None else 0
        if chips <= 0 or demand <= cfg.admission_load_guard * chips:
            self._log_admission(job, now, "fast_path", defers)
            return 0.0

        defer, reason, scores = self._evaluate_admission(
            self._capture(), job, now)
        decision = "defer" if defer else "admit"
        self._log_admission(job, now, decision, defers, reason=reason,
                            scores=scores)
        if defer:
            with self._lock:
                self._defer_counts[key] = defers + 1
            return defer_s
        return 0.0

    def _within_wait_budget(self, job, arrival_time: float, now: float,
                            defer_s: float) -> bool:
        """Whether one more deferral keeps the candidate's accumulated
        wait under admission_wait_budget of its fair-share budget.
        Serving services carry no epoch profile — their deferral is
        bounded by admission_max_defers alone."""
        sched = self._sched
        pos = getattr(job, "trace_position", None)
        profiles = sched._profiles
        if (pos is None or not profiles or pos >= len(profiles)
                or profiles[pos] is None):
            return True
        exclusive = sum(profiles[pos]["duration_every_epoch"])
        if exclusive <= 0:
            return True
        chips = len(sched.workers.worker_ids)
        cf = (max(1.0, (sched._num_jobs_in_trace + 1) / chips)
              if chips else 1.0)
        waited = now - getattr(job, "deferred_from", arrival_time)
        return ((waited + defer_s) / (exclusive * cf)
                <= self.cfg.admission_wait_budget)

    def _evaluate_admission(self, blob: bytes, job, now: float):
        """The with-vs-without Monte-Carlo core shared by the
        simulator's gate and the physical advisory path. Returns
        (defer, reason, scores)."""
        cfg = self.cfg
        horizon = cfg.admission_horizon_rounds
        # One candidate-inclusive contention reference for BOTH legs
        # (see _score).
        chips = len(self._sched.workers.worker_ids)
        cf = (max(1.0, (self._sched._num_jobs_in_trace + 1) / chips)
              if chips else 1.0)
        with_c, without_c = [], []
        for k in range(max(cfg.admission_samples, 1)):
            seed = self._seed(round(now), k)
            without_c.append(self._roll(blob, seed=seed,
                                        purpose="admission",
                                        horizon=horizon, cf=cf))
            with_c.append(self._roll(blob, seed=seed, purpose="admission",
                                     horizon=horizon, add_job=job, cf=cf))
        worst_with = max(s.worst_rho for s in with_c)
        worst_without = max(s.worst_rho for s in without_c)
        att_with = min(s.attainment for s in with_c)
        att_without = min(s.attainment for s in without_c)
        defer = False
        reason = None
        if (worst_with > cfg.admission_rho_limit
                and worst_with > worst_without + cfg.admission_min_gain):
            defer, reason = True, "ftf_envelope"
        elif (att_with < cfg.admission_slo_floor
                and att_without >= cfg.admission_slo_floor):
            defer, reason = True, "serving_slo"
        scores = {"worst_rho_with": round(worst_with, 6),
                  "worst_rho_without": round(worst_without, 6),
                  "attainment_with": round(att_with, 6),
                  "attainment_without": round(att_without, 6),
                  "samples": len(with_c)}
        return defer, reason, scores

    def advise_admission(self, blob: bytes, job, now: float) -> dict:
        """Physical-mode advisory verdict: the job was already admitted
        (deferral is a simulation-loop mechanism); `blob` is the
        PRE-admission fork its add_job captured, so the with/without
        comparison means the same thing it does in the simulator. The
        verdict lands in the decision log + journal as evidence."""
        defer, reason, scores = self._evaluate_admission(blob, job, now)
        decision = "would_defer" if defer else "admit"
        record = {"t": round(now, 3), "job_type": job.job_type,
                  "scale_factor": job.scale_factor, "mode": job.mode,
                  "decision": decision, "advisory": True}
        if reason:
            record["reason"] = reason
        record["scores"] = scores
        with self._lock:
            self.decision_log.append(record)
        self._sched.obs.inc(obs_names.WHATIF_ADMISSION_DECISIONS_TOTAL,
                            decision=decision)
        self._sched._emit_whatif_admission(record)
        return record

    def _log_admission(self, job, now: float, decision: str, defers: int,
                       reason: Optional[str] = None,
                       scores: Optional[dict] = None) -> None:
        sched = self._sched
        sched.obs.inc(obs_names.WHATIF_ADMISSION_DECISIONS_TOTAL,
                      decision=decision)
        record = {"t": round(now, 3), "job_type": job.job_type,
                  "scale_factor": job.scale_factor,
                  "mode": job.mode, "decision": decision,
                  "defers_so_far": defers}
        if reason:
            record["reason"] = reason
        if scores:
            record["scores"] = scores
        with self._lock:
            self.decision_log.append(record)
        sched._emit_whatif_admission(record)

    # ------------------------------------------------------------------
    # Round-boundary work (knob tuning, forecasts, capture hook)
    # ------------------------------------------------------------------

    def on_round_boundary(self, current_round: int, queued,
                          remaining_jobs: int) -> None:
        """Simulation hook: runs in the event loop at the clean fork
        point (heap drained, arrivals admitted, next round not yet
        scheduled). Physical mode drives the same work through
        maybe_capture_locked + run_background_step instead."""
        cfg = self.cfg
        with self._lock:
            want_capture = (cfg.capture_at_round is not None
                            and current_round == cfg.capture_at_round
                            and self.captured is None)
            want_tune = cfg.tune_knob is not None and (
                current_round - self._last_tune_round
                >= cfg.tune_interval_rounds)
            if want_tune:
                self._last_tune_round = current_round
            want_forecast = cfg.forecast_interval_rounds and (
                current_round - self._last_forecast_round
                >= cfg.forecast_interval_rounds)
            if want_forecast:
                self._last_forecast_round = current_round
        if want_capture:
            captured = (self._capture(),
                        pickle.loads(pickle.dumps(list(queued))),
                        remaining_jobs)
            with self._lock:
                self.captured = captured
        if want_tune:
            self.tune_once(current_round)
        if want_forecast:
            self.forecast_once(current_round)

    def tune_once(self, current_round: int,
                  blob: Optional[bytes] = None,
                  commit_lock=None) -> Optional[dict]:
        """One knob sweep: score every candidate on twin rollouts,
        commit the winner to the live scheduler, journal the evidence.
        Returns the sweep record (None when the knob does not apply
        yet, e.g. headroom before any serving service exists).
        `commit_lock` (physical mode) is taken around the live-state
        commit only — rollouts run on detached twins."""
        import contextlib
        sched = self._sched
        cfg = self.cfg
        knob = get_knob(cfg.tune_knob)
        if not knob.applicable(sched):
            return None
        if blob is None:
            blob = self._capture()
        current = knob.get(sched)
        candidates = [float(v) for v in
                      (cfg.tune_candidates or knob.candidates)]
        if current not in candidates:
            candidates = sorted(candidates + [current])
        sweep = []
        for value in candidates:
            scores = [self._roll(blob,
                                 seed=self._seed(current_round, i,
                                                 int(value * 1000)),
                                 purpose="tune",
                                 horizon=cfg.tune_horizon_rounds,
                                 knob=knob, knob_value=value)
                      for i in range(max(cfg.tune_samples, 1))]
            sweep.append({
                "value": value,
                # Worst case across samples: tuning must not commit a
                # value whose tail behavior regresses.
                "attainment": round(min(s.attainment for s in scores), 6),
                "worst_rho": round(max(s.worst_rho for s in scores), 6),
                "progress_steps": min(s.progress_steps for s in scores),
            })

        def objective(entry):
            # Serve the SLO first, then keep training fair, then fast.
            # Fairness compares at coarse (1%) granularity: sub-percent
            # rho noise between candidate rollouts must not outrank a
            # material training-progress difference.
            return (entry["attainment"], -round(entry["worst_rho"], 2),
                    entry["progress_steps"])

        best = max(sweep, key=objective)
        current_entry = next(e for e in sweep if e["value"] == current)
        # Hysteresis: commit a CHANGE only on a strict objective win —
        # ties keep the current value (no flapping between equals).
        chosen = (best["value"]
                  if objective(best) > objective(current_entry)
                  else current)
        changed = chosen != current
        with (commit_lock if commit_lock is not None
              else contextlib.nullcontext()):
            if changed:
                knob.set(sched, chosen)
                sched.obs.inc(obs_names.WHATIF_KNOB_COMMITS_TOTAL,
                              knob=knob.name)
            sched.obs.set_gauge(obs_names.WHATIF_KNOB_VALUE, chosen,
                                knob=knob.name)
            record = {"round": current_round, "knob": knob.name,
                      "previous": current, "chosen": chosen,
                      "changed": changed, "sweep": sweep}
            with self._lock:
                self.knob_log.append(record)
            # Durable (replayed) event: a resumed scheduler re-applies
            # the chosen value before its first round.
            sched._emit_whatif_knob(knob=knob.name, value=chosen,
                                    round=current_round, sweep=sweep)
        return record

    def forecast_once(self, current_round: int,
                      blob: Optional[bytes] = None) -> dict:
        """K seeded rollouts -> p50/p99 drain-time + attainment
        quantiles, exported as gauges (and /healthz via status())."""
        sched = self._sched
        cfg = self.cfg
        if blob is None:
            blob = self._capture()
        scores = [self._roll(blob, seed=self._seed(current_round, 7000 + k),
                             purpose="forecast",
                             horizon=cfg.forecast_horizon_rounds)
                  for k in range(max(cfg.forecast_samples, 1))]
        drains = [s.projected_drain_s for s in scores
                  if s.projected_drain_s is not None]
        attainments = [s.attainment for s in scores]
        record = {"round": current_round, "samples": len(scores)}
        if drains:
            record["makespan_p50"] = round(
                float(np.percentile(drains, 50)), 2)
            record["makespan_p99"] = round(
                float(np.percentile(drains, 99)), 2)
            sched.obs.set_gauge(obs_names.WHATIF_FORECAST_MAKESPAN_SECONDS,
                                record["makespan_p50"], quantile="p50")
            sched.obs.set_gauge(obs_names.WHATIF_FORECAST_MAKESPAN_SECONDS,
                                record["makespan_p99"], quantile="p99")
        record["attainment_p50"] = round(
            float(np.percentile(attainments, 50)), 6)
        # "p99" in SLO terms = the bad tail: the attainment only 1% of
        # sampled futures fall below.
        record["attainment_p99"] = round(
            float(np.percentile(attainments, 1)), 6)
        sched.obs.set_gauge(obs_names.WHATIF_FORECAST_ATTAINMENT,
                            record["attainment_p50"], quantile="p50")
        sched.obs.set_gauge(obs_names.WHATIF_FORECAST_ATTAINMENT,
                            record["attainment_p99"], quantile="p99")
        with self._lock:
            self.forecast_log.append(record)
        if cfg.shadow_chaos:
            self._shadow_chaos_once(current_round, blob)
        return record

    def _shadow_chaos_once(self, current_round: int, blob: bytes) -> None:
        """One seeded chaos probe against the twin: kill a random chip
        for part of the horizon and check the zero-failure-charge
        invariant (the PR 8 campaign's sharpest check), without ever
        touching the live cluster."""
        sched = self._sched
        rng = np.random.RandomState(self._seed(current_round, 424242))
        ids = sorted(sched.workers.worker_ids)
        if not ids:
            return
        victim = ids[int(rng.randint(len(ids)))]
        wt = sched.workers.id_to_type[victim]
        now = sched.get_current_timestamp()
        round_s = sched._time_per_iteration
        events = [
            {"at": now + round_s, "kill": [victim]},
            {"at": now + round_s * max(
                2, self.cfg.forecast_horizon_rounds // 2),
             "revive": [victim], "worker_type": wt}]
        outcome = "ok"
        detail = None
        try:
            # Differential, like the chaos campaign's sharpest check: a
            # fault-free baseline rollout of the SAME seed establishes
            # how many failed aggregates the workload accrues on its
            # own, and the injected fault must add ZERO on top. (Each
            # thawed twin carries a fresh obs bundle, so the counters
            # reflect the rollouts alone.)
            seed = self._seed(current_round, 515151)
            baseline = fork.thaw(sched, blob, seed=seed)
            fork.rollforward(
                baseline, horizon_rounds=self.cfg.forecast_horizon_rounds)
            base_failed = baseline.obs.registry.value(
                obs_names.MICROTASKS_TOTAL, outcome="failed")
            twin = fork.thaw(sched, blob, seed=seed)
            fork.rollforward(
                twin, horizon_rounds=self.cfg.forecast_horizon_rounds,
                fault_events=events)
            with self._lock:
                self.rollouts += 2
            sched.obs.inc(obs_names.WHATIF_ROLLOUTS_TOTAL, amount=2,
                          purpose="shadow_chaos")
            failed = twin.obs.registry.value(
                obs_names.MICROTASKS_TOTAL, outcome="failed")
            if failed > base_failed:
                outcome = "violation"
                detail = (f"injected kill added {failed - base_failed:.0f}"
                          " failure charge(s) over the fault-free "
                          "baseline")
        except Exception as e:  # noqa: BLE001 - a twin crash IS the finding
            outcome = "violation"
            detail = f"twin rollout raised {type(e).__name__}: {e}"
        sched.obs.inc(obs_names.WHATIF_SHADOW_CHAOS_TOTAL, outcome=outcome)
        record = {"round": current_round, "victim": victim,
                  "outcome": outcome}
        if detail:
            record["detail"] = detail
        with self._lock:
            self.shadow_log.append(record)

    # ------------------------------------------------------------------
    # Physical-mode split (capture under lock; roll on a thread)
    # ------------------------------------------------------------------

    def maybe_capture_locked(self) -> Optional[Tuple[str, int, bytes]]:
        """Called from the physical round pipeline UNDER the scheduler
        lock: decide whether this round owes background work and, if
        so, pay only the state-copy cost here. Returns (kind, round,
        blob) for the background thread, or None."""
        cfg = self.cfg
        current_round = self._sched.rounds.num_completed_rounds
        with self._lock:
            kind = None
            if cfg.tune_knob is not None and (
                    current_round - self._last_tune_round
                    >= cfg.tune_interval_rounds):
                self._last_tune_round = current_round
                kind = "tune"
            elif cfg.forecast_interval_rounds and (
                    current_round - self._last_forecast_round
                    >= cfg.forecast_interval_rounds):
                self._last_forecast_round = current_round
                kind = "forecast"
        if kind is not None:
            return (kind, current_round, self._capture())
        return None

    def run_background_step(self, work: Tuple[str, int, bytes],
                            commit_lock=None) -> None:
        """Physical background thread body: roll the captured blob OFF
        the lock; only tune_once's live-state commit re-takes
        `commit_lock` (see PhysicalScheduler._whatif_loop)."""
        kind, current_round, blob = work
        if kind == "tune":
            self.tune_once(current_round, blob=blob,
                           commit_lock=commit_lock)
        elif kind == "forecast":
            self.forecast_once(current_round, blob=blob)

    # ------------------------------------------------------------------
    # Status (drivers, /healthz)
    # ------------------------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            out = {
                "admission": self.cfg.admission,
                "forks": self.forks,
                "rollouts": self.rollouts,
                "max_fork_s": round(self.max_fork_s, 6),
                "decisions": len(self.decision_log),
                # Physical advisory verdicts count too (would_defer).
                "deferrals": sum(1 for d in self.decision_log
                                 if d["decision"] in ("defer",
                                                      "would_defer")),
            }
            if self.knob_log:
                out["knob"] = self.knob_log[-1]
            if self.forecast_log:
                out["forecast"] = self.forecast_log[-1]
            if self.shadow_log:
                out["shadow_chaos"] = self.shadow_log[-1]
        return out


__all__ = ["WhatIfPlane", "WhatIfConfig", "RolloutScore"]
