"""Throughput oracle files.

Format (reference: scheduler/utils.py:575-594 and *_throughputs.json):

    {worker_type: {"('<job_type>', <scale_factor>)":
        {"null": isolated_tput,
         "('<other_job_type>', <sf>)": [tput_self, tput_other]}}}

Keys are stringified (job_type, scale_factor) tuples; "null" holds the
isolated throughput in steps/sec.

A top-level "__meta__" entry (not in the reference format) carries
measurement metadata alongside the numbers it calibrates, e.g.

    {"__meta__": {"dispatch_overhead_s": {"cpu": 22.4},
                  "measured_at": "...", ...}, "cpu": {...}}

`dispatch_overhead_s` is the measured per-dispatch dead time per
worker type: the full spawn -> exit wall time of a 1-step run
(interpreter + jax import, data load, checkpoint restore, first-step
compile, and the exit-path checkpoint save) as measured by
scripts/profiling/measure_startup.py. `lease_shortfall_s` (+
`lease_shortfall_s_by_type`) is the deployed-conditions in-lease
shortfall measured through the real runtime by
scripts/profiling/measure_deployed.py — a different quantity under a
deliberately different key, preferred by the simulator's calibrated
overhead model when both are present (sched/scheduler.py
`_cold_dispatch_overhead`). `read_throughputs` skips the entry so
every existing consumer sees the plain oracle mapping.
"""
from __future__ import annotations

import json
import re
from typing import Dict, Optional, Tuple

JobTypeKey = Tuple[str, int]

_KEY_RE = re.compile(r"\('(.*)', (\d+)\)")


def parse_job_type_tuple(s: str) -> Optional[JobTypeKey]:
    m = _KEY_RE.match(s)
    if m is None:
        return None
    return (m.group(1), int(m.group(2)))


def read_oracle(path: str) -> Tuple[Dict[str, Dict[JobTypeKey, dict]], dict]:
    """Load an oracle file once: (throughputs, __meta__ or {})."""
    with open(path) as f:
        raw = json.load(f)
    meta = raw.get("__meta__", {})
    if not isinstance(meta, dict):
        raise ValueError(f"__meta__ in {path} must be an object")
    out: Dict[str, Dict[JobTypeKey, dict]] = {}
    for worker_type, per_type in raw.items():
        if worker_type == "__meta__":
            continue
        parsed = {}
        for job_type_str, entry in per_type.items():
            key = parse_job_type_tuple(job_type_str)
            if key is None:
                raise ValueError(f"bad job type key {job_type_str!r}")
            parsed_entry = {}
            for other, tput in entry.items():
                parsed_entry["null" if other == "null" else parse_job_type_tuple(other)] = tput
            parsed[key] = parsed_entry
        out[worker_type] = parsed
    return out, meta


def read_throughputs(path: str) -> Dict[str, Dict[JobTypeKey, dict]]:
    """Load an oracle file, parsing stringified keys into tuples."""
    return read_oracle(path)[0]


def read_oracle_meta(path: str) -> dict:
    """The oracle file's "__meta__" entry ({} when absent)."""
    return read_oracle(path)[1]


def write_throughputs(path: str, throughputs: Dict[str, Dict[JobTypeKey, dict]]) -> None:
    raw = {
        worker_type: {
            str(key): {
                ("null" if other == "null" else str(other)): tput
                for other, tput in entry.items()
            }
            for key, entry in per_type.items()
        }
        for worker_type, per_type in throughputs.items()
    }
    with open(path, "w") as f:
        json.dump(raw, f, indent=2)
