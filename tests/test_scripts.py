"""Smoke tests for the driver / sweep / plotting / reproduce tooling."""
import json
import os
import pickle
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
THROUGHPUTS = os.path.join(REPO, "data", "tacc_throughputs.json")


def run_script(args, timeout=600):
    # Children stay off the accelerator relay (a wedged tunnel would
    # hang their jax import); tests that need the ambient backend build
    # their env explicitly with ambient_accelerator_env().
    from conftest import cpu_subprocess_env
    out = subprocess.run([sys.executable, *args], capture_output=True,
                         text=True, timeout=timeout, cwd=REPO,
                         env=cpu_subprocess_env())
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


def fake_metrics(makespan=1000.0, n=10):
    return {
        "makespan": makespan,
        "avg_jct": makespan / 2,
        "geometric_mean_jct": makespan / 3,
        "jct_list": [makespan / 2 + 10 * i for i in range(n)],
        "finish_time_fairness_list": [0.8 + 0.05 * i for i in range(n)],
        "finish_time_fairness_themis_list": [0.9 + 0.05 * i for i in range(n)],
        "cluster_util": 0.7,
        "utilization_list": [0.5, 0.7, 0.9],
        "extension_percentage": 42.0,
        "per_round_schedule": [{0: (0,), 1: (1, 2)}, {1: (1, 2)}],
    }


class TestGeneratedJobsDriver:
    def test_runs_and_reports(self):
        out = run_script(["scripts/drivers/simulate_generated.py",
                          "--num_jobs", "8", "--policy", "isolated",
                          "--throughputs", THROUGHPUTS,
                          "--cluster_spec", "v100:8",
                          "--round_duration", "120"])
        result = json.loads(out.strip().splitlines()[-1])
        assert result["makespan"] > 0
        assert result["num_jobs"] == 8

    def test_seeded_determinism(self):
        args = ["scripts/drivers/simulate_generated.py", "--num_jobs", "6",
                "--policy", "fifo", "--throughputs", THROUGHPUTS,
                "--cluster_spec", "v100:4", "--round_duration", "120",
                "--seed", "7"]
        a = json.loads(run_script(args).strip().splitlines()[-1])
        b = json.loads(run_script(args).strip().splitlines()[-1])
        assert a == b


class TestPolicyRuntimeSweep:
    def test_all_default_policies_solve(self):
        out = run_script(["scripts/microbenchmarks/sweep_policy_runtimes.py",
                          "--num_jobs", "8", "--cluster_sizes", "8",
                          "--trials", "1"])
        rows = [json.loads(line) for line in out.strip().splitlines()]
        assert len(rows) == 8  # default policy list
        assert all("best_s" in r for r in rows)

    def test_multi_worker_types(self):
        out = run_script(["scripts/microbenchmarks/sweep_policy_runtimes.py",
                          "--policies", "max_min_fairness_perf",
                          "--num_jobs", "8", "--cluster_sizes", "6",
                          "--num_worker_types", "3", "--trials", "1"])
        assert "best_s" in out


class TestMilpAssemblyBench:
    def test_smoke_both_assemblers(self, tmp_path):
        """The assembly/solve-split bench runs for both assembler arms,
        honors --smoke, and dumps the obs histograms."""
        metrics = tmp_path / "assembly.prom"
        out = run_script(["scripts/microbenchmarks/bench_milp_assembly.py",
                          "--num_jobs", "24", "--trials", "1",
                          "--skip_solve", "--smoke",
                          "--metrics_out", str(metrics)])
        row = json.loads(out.strip().splitlines()[-1])
        assert row["assembler"] == "vectorized"
        assert row["assembly_best_s"] < row["solve_budget_floor_s"]
        assert "swtpu_milp_assembly_seconds" in metrics.read_text()
        out = run_script(["scripts/microbenchmarks/bench_milp_assembly.py",
                          "--num_jobs", "24", "--trials", "1",
                          "--skip_solve", "--assembler", "loop"])
        assert json.loads(out.strip().splitlines()[-1])["assembler"] == "loop"


class TestAnalysisBench:
    def test_smoke_gate_and_row_shape(self, tmp_path):
        """bench_analysis honors --smoke and reports cold/warm wall +
        the per-pass table (the analyzer-performance floor)."""
        out_file = tmp_path / "analysis.json"
        out = run_script(["scripts/microbenchmarks/bench_analysis.py",
                          "--smoke", "--runs", "1",
                          "--max_cold_s", "30", "--max_warm_s", "20",
                          "--output", str(out_file)])
        row = json.loads(out.strip().splitlines()[-1])
        assert row["findings"] == 0
        assert row["warm_wall_s"] <= row["cold_wall_s"] * 1.5
        assert "race-detector" in row["per_pass_wall_s"]
        assert "suppression-audit" in row["per_pass_wall_s"]
        assert json.loads(out_file.read_text())["bench"] == "analysis"

    def test_smoke_fails_above_ceiling(self):
        out = subprocess.run(
            [sys.executable,
             "scripts/microbenchmarks/bench_analysis.py", "--smoke",
             "--runs", "1", "--max_cold_s", "0.000001"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1
        assert "SMOKE FAIL" in out.stderr


class TestTracingBench:
    def test_smoke_gate_and_row_shape(self):
        """bench_tracing honors --smoke and emits the bench.py row
        fields (spans/s floor + per-round overhead ceiling)."""
        out = run_script(["scripts/microbenchmarks/bench_tracing.py",
                          "--smoke", "--spans", "5000",
                          "--propagations", "2000", "--flushes", "2",
                          "--min_spans_per_s", "1000"])
        row = json.loads(out.strip().splitlines()[-1])
        for key in ("spans_per_s", "propagate_mean_us",
                    "shard_flush_mean_s", "round_overhead_est_s"):
            assert key in row
        assert row["spans_per_s"] > 1000

    def test_smoke_fails_below_floor(self):
        out = subprocess.run(
            [sys.executable,
             "scripts/microbenchmarks/bench_tracing.py", "--smoke",
             "--spans", "2000", "--propagations", "1000",
             "--flushes", "1", "--min_spans_per_s", "1e12"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1
        assert "SMOKE FAIL" in out.stderr


class TestOracleBench:
    def test_smoke_gate_and_row_shape(self):
        """bench_oracle honors --smoke and emits the bench.py row
        fields (fit-wall ceiling + predictions/s floor)."""
        out = run_script(["scripts/microbenchmarks/bench_oracle.py",
                          "--smoke", "--fits", "2", "--copies", "2",
                          "--predictions", "2000",
                          "--observations", "2000",
                          "--min_predictions_per_s", "500"])
        row = json.loads(out.strip().splitlines()[-1])
        for key in ("mean_fit_s", "rmse", "predictions_per_s",
                    "observations_per_s"):
            assert key in row
        assert row["predictions_per_s"] > 500
        assert row["rmse"] < 0.2  # log-space fit of a log-linear surface

    def test_smoke_fails_below_floor(self):
        out = subprocess.run(
            [sys.executable,
             "scripts/microbenchmarks/bench_oracle.py", "--smoke",
             "--fits", "1", "--copies", "1", "--predictions", "500",
             "--observations", "500",
             "--min_predictions_per_s", "1e12"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1
        assert "SMOKE FAIL" in out.stderr


class TestPlotting:
    def test_all_plot_kinds(self, tmp_path):
        from shockwave_tpu import plotting
        results = {"a": fake_metrics(1000.0), "b": fake_metrics(1500.0)}
        assert os.path.exists(plotting.plot_jct_cdf(
            results, str(tmp_path / "jct.png")))
        assert os.path.exists(plotting.plot_ftf_cdf(
            results, str(tmp_path / "ftf.png")))
        assert os.path.exists(plotting.plot_policy_bars(
            results, str(tmp_path / "bars.png")))
        assert os.path.exists(plotting.plot_utilization(
            results, str(tmp_path / "util.png")))
        assert os.path.exists(plotting.plot_schedule_heatmap(
            fake_metrics(), str(tmp_path / "heat.png")))


class TestReproduceTooling:
    def test_aggregate_result(self, tmp_path):
        for policy in ("shockwave", "max_min_fairness"):
            with open(tmp_path / f"{policy}.pkl", "wb") as f:
                pickle.dump(fake_metrics(), f)
        out = run_script(["reproduce/aggregate_result.py", str(tmp_path)])
        assert "Shockwave" in out and "Gavel" in out

    def test_fidelity_pass_and_fail(self, tmp_path):
        phys, sim = tmp_path / "p.pkl", tmp_path / "s.pkl"
        with open(phys, "wb") as f:
            pickle.dump(fake_metrics(1000.0), f)
        with open(sim, "wb") as f:
            pickle.dump(fake_metrics(1040.0), f)
        out = run_script(["reproduce/analyze_fidelity.py", str(phys),
                          str(sim), "--tolerance", "0.10"])
        assert "within tolerance" in out
        from conftest import cpu_subprocess_env
        bad = subprocess.run(
            [sys.executable, "reproduce/analyze_fidelity.py", str(phys),
             str(sim), "--tolerance", "0.01"],
            capture_output=True, text=True, cwd=REPO,
            env=cpu_subprocess_env())
        assert bad.returncode == 1


@pytest.mark.slow
class TestProfiler:
    def test_profiles_lm(self, tmp_path):
        out_path = tmp_path / "oracle.json"
        run_script(["scripts/profiling/measure_throughput.py",
                    "--worker_type", "test", "--output", str(out_path),
                    "--families", "LM", "--scale_factors", "1",
                    "--steps", "3", "--warmup", "1"], timeout=1200)
        from shockwave_tpu.core.oracle import read_throughputs
        oracle = read_throughputs(str(out_path))
        assert oracle["test"][("LM (batch size 5)", 1)]["null"] > 0


class TestExtrapolateSf:
    def test_adds_estimated_rows_with_provenance(self, tmp_path):
        """sf>1 rows derived from measured sf=1 rates x the reference
        oracle's measured scaling efficiency, recorded as estimates."""
        oracle = {"v5e": {"('Transformer (batch size 64)', 1)":
                          {"null": 10.0}}}
        path = tmp_path / "o.json"
        path.write_text(json.dumps(oracle))
        run_script([os.path.join(REPO, "scripts/profiling/extrapolate_sf.py"),
                    "--oracle", str(path), "--worker_type", "v5e"])
        got = json.loads(path.read_text())
        rows = got["v5e"]
        ref = json.load(open(THROUGHPUTS))["v100"]
        base = ref["('Transformer (batch size 64)', 1)"]["null"]
        for sf in (2, 4, 8):
            key = f"('Transformer (batch size 64)', {sf})"
            eff = ref[key]["null"] / (base * sf)
            assert rows[key]["null"] == pytest.approx(10.0 * sf * eff,
                                                      rel=1e-3)
            assert key in got["__meta__"]["estimated_rows"]["v5e"]

    def test_never_overwrites_measured_rows(self, tmp_path):
        oracle = {"v5e": {"('Transformer (batch size 64)', 1)":
                          {"null": 10.0},
                          "('Transformer (batch size 64)', 4)":
                          {"null": 123.0}}}
        path = tmp_path / "o.json"
        path.write_text(json.dumps(oracle))
        run_script([os.path.join(REPO, "scripts/profiling/extrapolate_sf.py"),
                    "--oracle", str(path), "--worker_type", "v5e"])
        got = json.loads(path.read_text())
        assert got["v5e"]["('Transformer (batch size 64)', 4)"][
            "null"] == 123.0
        assert ("('Transformer (batch size 64)', 4)"
                not in got["__meta__"]["estimated_rows"]["v5e"])


class TestMeasureDeployedParser:
    def test_parse_rounds_extracts_lease_records(self, tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "measure_deployed",
            os.path.join(REPO, "scripts/profiling/measure_deployed.py"))
        md = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(md)

        log_dir = tmp_path / "job_id=0" / ".swtpu" / "round=1"
        log_dir.mkdir(parents=True)
        (log_dir / "worker=0.log").write_text(
            "[2026-07-30 10:00:00] [PROGRESS] [STEPS] 0\n"
            "[2026-07-30 10:00:00] [LOAD CHECKPOINT] [BEGIN] \n"
            "[2026-07-30 10:00:01] [LOAD CHECKPOINT] [END] \n"
            "[2026-07-30 10:02:00] [LEASE] [EXPIRED] 31 / 70 steps, "
            "104.6354 / 104.6354 seconds\n"
            "[2026-07-30 10:02:02] [SAVE CHECKPOINT] [BEGIN] \n"
            "[2026-07-30 10:02:03] [SAVE CHECKPOINT] [END] \n")
        recs = md.parse_rounds(str(tmp_path))
        assert len(recs) == 1
        rnd, load, exp, save_end, steps, dur = recs[0]
        assert rnd == 1 and steps == 31
        assert dur == pytest.approx(104.6354)
        assert (save_end - load).total_seconds() == 122.0


class TestBenchTpuFallback:
    def test_merges_newest_committed_artifact(self, tmp_path, monkeypatch):
        """With the chip unreachable, bench.py must report the newest
        committed raw measurement, provenance-marked (tpu_as_of)."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)

        tpu_dir = tmp_path / "reproduce" / "tpu"
        tpu_dir.mkdir(parents=True)
        (tpu_dir / "bench_TPU_v5_lite_20260101T000000Z.json").write_text(
            json.dumps({"measured_at": "2026-01-01T00:00:00+00:00",
                        "transformer_steps_per_s": 10.0}))
        (tpu_dir / "bench_TPU_v5_lite_20260301T000000Z.json").write_text(
            json.dumps({"measured_at": "2026-03-01T00:00:00+00:00",
                        "transformer_steps_per_s": 52.8,
                        "transformer_mfu": 0.33}))
        monkeypatch.setattr(bench, "REPO", str(tmp_path))
        got = bench.committed_tpu_result()
        assert got["transformer_steps_per_s"] == 52.8
        assert got["tpu_as_of"] == "2026-03-01T00:00:00+00:00"
        assert got["tpu_source"].endswith("20260301T000000Z.json")

    def test_empty_dir_gives_nothing(self, tmp_path, monkeypatch):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bench", os.path.join(REPO, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        monkeypatch.setattr(bench, "REPO", str(tmp_path))
        assert bench.committed_tpu_result() == {}


class TestGraftEntry:
    def test_dryrun_multichip_with_unset_jax_platforms(self):
        """The driver leaves JAX_PLATFORMS unset and an accelerator plugin
        may auto-register via PYTHONPATH; the dryrun must still build its
        8-device virtual CPU mesh (round-1/2 gate failure regression)."""
        from conftest import ambient_accelerator_env
        out = subprocess.run(
            [sys.executable, "-c",
             "from __graft_entry__ import dryrun_multichip; "
             "dryrun_multichip(8)"],
            capture_output=True, text=True, timeout=600, cwd=REPO,
            env=ambient_accelerator_env())
        assert out.returncode == 0, out.stderr[-2000:]
        assert "dryrun_multichip(8)" in out.stdout


class TestServingDecodeBench:
    def test_smoke_gate_and_row_shape(self, tmp_path):
        """bench_serving_decode honors --smoke and emits the bench.py
        row fields (the ROADMAP tokens/s-per-chip number)."""
        out_file = tmp_path / "decode.json"
        out = run_script(["scripts/microbenchmarks/bench_serving_decode.py",
                          "--smoke", "--steps", "4", "--warmup", "1",
                          "--batch_size", "4", "--tokens_per_request", "16",
                          "--min_tokens_per_s", "50",
                          "--output", str(out_file)])
        row = json.loads(out.strip().splitlines()[-1])
        for key in ("tokens_per_s", "tokens_per_s_per_chip",
                    "requests_per_s", "backend"):
            assert key in row
        assert row["tokens_per_s"] > 50
        assert json.loads(out_file.read_text())["bench"] == "serving_decode"

    def test_smoke_fails_below_floor(self):
        from conftest import cpu_subprocess_env
        out = subprocess.run(
            [sys.executable,
             "scripts/microbenchmarks/bench_serving_decode.py", "--smoke",
             "--steps", "2", "--warmup", "1", "--batch_size", "2",
             "--tokens_per_request", "8", "--min_tokens_per_s", "1e15"],
            capture_output=True, text=True, cwd=REPO,
            env=cpu_subprocess_env())
        assert out.returncode == 1
        assert "SMOKE FAIL" in out.stderr


class TestServingMeasuredCalibrationDriver:
    def test_byte_stable_and_envelope_checked(self, tmp_path):
        """Two runs of the calibration study produce byte-identical
        artifacts (the CI cmp gate) and pass their own envelope
        --check; coverage > 0 rides in the artifact."""
        args = ["scripts/drivers/serving_measured_calibration.py",
                "--rhos", "0.4,0.8", "--replicas", "1,2",
                "--horizon_s", "400", "--check"]
        a, b = tmp_path / "cal_a.json", tmp_path / "cal_b.json"
        run_script(args + ["--out", str(a)])
        run_script(args + ["--out", str(b)])
        assert a.read_bytes() == b.read_bytes()
        doc = json.loads(a.read_text())
        assert doc["measured_sample_coverage"] > 0
        assert doc["merge_order_independent"] is True
        assert len(doc["rows"]) == 4

    def test_check_fails_outside_envelope(self, tmp_path):
        from conftest import cpu_subprocess_env
        out = subprocess.run(
            [sys.executable,
             "scripts/drivers/serving_measured_calibration.py",
             "--rhos", "0.4", "--replicas", "4", "--horizon_s", "300",
             "--envelope", "0.9:1.1", "--check",
             "--out", str(tmp_path / "cal.json")],
            capture_output=True, text=True, cwd=REPO,
            env=cpu_subprocess_env())
        assert out.returncode == 1
        assert "CHECK FAIL" in out.stderr

    def test_committed_artifact_reproduces(self, tmp_path):
        """The committed calibration study is exactly what the driver
        produces at its defaults (minus the loopback section, which CI
        exercises live)."""
        committed_path = os.path.join(REPO, "reproduce", "serving",
                                      "measured_calibration.json")
        committed = json.loads(open(committed_path).read())
        out_path = tmp_path / "cal.json"
        run_script(["scripts/drivers/serving_measured_calibration.py",
                    "--out", str(out_path)])
        fresh = json.loads(out_path.read_text())
        committed.pop("loopback", None)
        assert fresh == committed
