#!/usr/bin/env python3
"""What-if control-plane overhead microbenchmark: forks/min +
rollouts/min on a mid-run canonical scheduler.

Measures the two costs the what-if plane charges the control plane:

- **fork** — `whatif.fork.capture` (the journal-snapshot pickle; the
  part that runs under the scheduler lock in physical mode) plus
  `thaw` (twin materialization, off the lock),
- **rollout** — `fork.rollforward` of one thawed twin over a fixed
  horizon (the unit of every admission sample / knob candidate /
  forecast draw).

The subject is the canonical 120-job trace run to a mid-run round
(like bench_sim_round.py, the round-bookkeeping microbenchmark this
sits beside), so the forked state carries a realistic active set.
Prints ONE JSON line; bench.py embeds it as the `whatif_phase` row.
``--smoke`` exits nonzero when the fork wall exceeds --max_fork_s
(CI guard: the state copy must stay far under a physical round).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402
from shockwave_tpu.sched import Scheduler, SchedulerConfig  # noqa: E402
from shockwave_tpu.solver import get_policy  # noqa: E402
from shockwave_tpu.whatif import fork  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def mid_run_scheduler(args):
    """The canonical trace advanced to --capture_round, captured via
    the plane's fork hook (the clean round-boundary fork point)."""
    jobs, arrivals = parse_trace(args.trace)
    if args.num_jobs:
        jobs, arrivals = jobs[:args.num_jobs], arrivals[:args.num_jobs]
    profiles = build_profiles(jobs, read_throughputs(args.throughputs))
    sched = Scheduler(
        get_policy(args.policy, seed=0), simulate=True,
        throughputs_file=args.throughputs, profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=args.round_duration, seed=0,
            max_rounds=args.capture_round + 1,
            whatif={"capture_at_round": args.capture_round}))
    sched.simulate({"v100": args.num_chips}, arrivals, jobs)
    if sched._whatif.captured is None:
        raise SystemExit(f"trace drained before round "
                         f"{args.capture_round}; lower --capture_round")
    return sched


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--trace",
                   default=os.path.join(REPO,
                                        "data/canonical_120job.trace"))
    p.add_argument("--throughputs",
                   default=os.path.join(REPO,
                                        "data/tacc_throughputs.json"))
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--num_jobs", type=int, default=0,
                   help="trace-head subset (0 = full trace)")
    p.add_argument("--num_chips", type=int, default=32)
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--capture_round", type=int, default=40)
    p.add_argument("--forks", type=int, default=20)
    p.add_argument("--rollouts", type=int, default=10)
    p.add_argument("--horizon_rounds", type=int, default=12)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--max_fork_s", type=float, default=0.5,
                   help="--smoke: fail when one fork's capture exceeds "
                        "this (the lock-held cost in physical mode)")
    args = p.parse_args()
    setup_logging("warning")

    sched = mid_run_scheduler(args)
    blob, queued, remaining = sched._whatif.captured

    t0 = time.monotonic()
    for _ in range(args.forks):
        fork.thaw(sched, fork.capture(sched))
    fork_wall = time.monotonic() - t0

    capture_wall = 0.0
    worst_capture = 0.0
    for _ in range(args.forks):
        c0 = time.monotonic()
        fork.capture(sched)
        dt = time.monotonic() - c0
        capture_wall += dt
        worst_capture = max(worst_capture, dt)
    t0 = time.monotonic()
    for k in range(args.rollouts):
        twin = fork.thaw(sched, blob, seed=k)
        fork.rollforward(twin, horizon_rounds=args.horizon_rounds,
                         remaining_jobs=remaining)
    rollout_wall = time.monotonic() - t0

    mean_capture = capture_wall / max(args.forks, 1)
    line = {
        "active_jobs_at_fork": len(sched.acct.jobs),
        "capture_round": args.capture_round,
        "forks": args.forks,
        "fork_wall_s": round(fork_wall, 3),
        "mean_capture_s": round(mean_capture, 5),
        "max_capture_s": round(worst_capture, 5),
        "forks_per_min": round(args.forks / fork_wall * 60.0, 1)
        if fork_wall > 0 else None,
        "rollouts": args.rollouts,
        "horizon_rounds": args.horizon_rounds,
        "rollout_wall_s": round(rollout_wall, 3),
        "rollouts_per_min": round(args.rollouts / rollout_wall * 60.0, 1)
        if rollout_wall > 0 else None,
    }
    print(json.dumps(line))
    if args.smoke and worst_capture > args.max_fork_s:
        print(f"SMOKE FAIL: worst capture {worst_capture:.3f}s > "
              f"{args.max_fork_s}s", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
