"""Offline trainer: ``python -m shockwave_tpu.oracle.train``.

Reads one or more telemetry-history rings (``/history.json`` payloads,
obs/history.py) and fits a `ThroughputModel` from their per-microtask
observation rows. Foreign, legacy or malformed rows are **skipped with
a warning**, never a KeyError: the history file is an operational
artifact that outlives schema changes, and a trainer that dies on one
stale row cannot be run from cron.

Emits one JSON summary line on stdout (row counts, vocab sizes, fit
RMSE, output path) so drivers and CI can assert on the result.
"""
from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import List, Tuple

from ..obs.history import (HISTORY_SCHEMA, OBSERVATIONS_SCHEMA,
                           valid_observation)
from .model import DEFAULT_RIDGE, ThroughputModel

logger = logging.getLogger("shockwave_tpu.oracle")


def load_training_rows(paths: List[str]) -> Tuple[List[tuple], int]:
    """(training rows, skipped count) from history payload files.

    A row trains iff it passes `obs.history.valid_observation` AND its
    rate is positive; everything else — foreign file schemas, a future
    observations_schema, malformed or non-positive rows — is counted
    and warned about once per file, not raised.
    """
    rows: List[tuple] = []
    skipped = 0
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError) as exc:
            logger.warning("skipping history %s: %s", path, exc)
            continue
        if not isinstance(payload, dict):
            logger.warning("skipping history %s: not an object", path)
            continue
        if payload.get("schema") != HISTORY_SCHEMA:
            logger.warning(
                "skipping history %s: schema %r (this build reads %d)",
                path, payload.get("schema"), HISTORY_SCHEMA)
            continue
        obs_schema = payload.get("observations_schema")
        if obs_schema not in (None, OBSERVATIONS_SCHEMA):
            # None is a pre-versioning ring: its rows still validate
            # individually below. A *different* version does not.
            logger.warning(
                "skipping observations of %s: observations_schema %r "
                "(this build reads %d)", path, obs_schema,
                OBSERVATIONS_SCHEMA)
            continue
        bad = 0
        for entry in payload.get("observations", []):
            if not valid_observation(entry) or float(entry[5]) <= 0.0:
                bad += 1
                continue
            _round, job_type, bs, sf, wt, rate = entry
            rows.append((job_type, bs, int(sf), wt, float(rate)))
        if bad:
            logger.warning(
                "skipped %d foreign/legacy/malformed observation rows "
                "in %s", bad, path)
            skipped += bad
    return rows, skipped


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Fit the learned throughput model from telemetry "
                    "history rings")
    parser.add_argument("--history", nargs="+", required=True,
                        help="history.json payload file(s)")
    parser.add_argument("--out", required=True,
                        help="model JSON output path")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--ridge", type=float, default=DEFAULT_RIDGE)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(levelname)s %(name)s: %(message)s")

    rows, skipped = load_training_rows(args.history)
    if not rows:
        print(json.dumps({"error": "no usable training rows",
                          "skipped_rows": skipped}))
        return 1
    model = ThroughputModel.fit(rows, seed=args.seed, ridge=args.ridge)
    model.save(args.out)
    print(json.dumps({
        "rows": len(rows),
        "skipped_rows": skipped,
        "families": len(model.families),
        "worker_types": len(model.worker_types),
        "generations": len(model.generations),
        "rmse": model.rmse,
        "out": args.out,
    }, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
