from .lease import Lease

__all__ = ["Lease"]
