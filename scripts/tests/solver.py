#!/usr/bin/env python3
"""Standalone policy consistency check on random job populations.

Generates random jobs and validates every registered policy's allocation
against the cluster invariants — per-job allocation in [0, 1], worker
capacity respected, effective throughput non-negative — and prints a
per-policy summary (reference: scheduler/scripts/tests/solver.py, which
compared per-job vs per-job-type formulations; here the invariant check
covers the full registry).

    python scripts/tests/solver.py --num_jobs 24 --num_workers 16 --trials 3
"""
import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.job import JobIdPair
from shockwave_tpu.solver import get_policy

# Share-hint policies: allocations are entitlement hints whose cluster-wide
# sum can exceed capacity (the round mechanism enforces limits; Gandiva
# additionally space-shares chips). Matches the reference's proportional /
# gandiva_fair formulations (policies/proportional.py:33-41).
SHARE_HINT = {"proportional", "gandiva", "gandiva_fair"}

POLICIES = [
    "isolated", "isolated_plus", "proportional", "fifo", "fifo_perf",
    "max_min_fairness", "max_min_fairness_perf",
    "max_min_fairness_strategy_proof", "max_min_fairness_water_filling",
    "finish_time_fairness", "min_total_duration", "max_sum_throughput_perf",
    "gandiva", "gandiva_fair", "allox",
]


def random_state(num_jobs, num_workers, seed):
    rng = random.Random(seed)
    job_ids = [JobIdPair(i) for i in range(num_jobs)]
    throughputs = {j: {"v100": rng.uniform(0.5, 60.0)} for j in job_ids}
    sfs = {j: rng.choices([1, 2, 4, 8], weights=[0.7, 0.1, 0.15, 0.05])[0]
           for j in job_ids}
    prios = {j: 1.0 for j in job_ids}
    cluster = {"v100": num_workers}
    return job_ids, throughputs, sfs, prios, cluster


def allocate(policy_name, throughputs, sfs, prios, cluster, seed):
    policy = get_policy(policy_name, seed=seed)
    times = {j: 0.0 for j in sfs}
    steps = {j: 10_000 for j in sfs}
    if policy_name == "proportional":
        return policy.get_allocation(throughputs, cluster)
    if policy_name in ("isolated", "isolated_plus", "gandiva",
                       "gandiva_fair") or policy_name.startswith("fifo"):
        return policy.get_allocation(throughputs, sfs, cluster)
    if policy_name.startswith("allox"):
        return policy.get_allocation(throughputs, sfs, times, steps, [],
                                     cluster)
    if policy_name.startswith("min_total_duration"):
        return policy.get_allocation(throughputs, sfs, steps, cluster)
    if policy_name == "max_sum_throughput_perf":
        return policy.get_allocation(throughputs, sfs, cluster)
    if policy_name.startswith("finish_time_fairness"):
        return policy.get_allocation(throughputs, sfs, prios, times, steps,
                                     cluster)
    return policy.get_allocation(throughputs, sfs, prios, cluster)


def check(alloc, job_ids, sfs, cluster, tol=1e-4):
    problems = []
    if alloc is None:
        return ["allocation is None"]
    for j, per_type in alloc.items():
        for wt, x in per_type.items():
            if x < -tol or x > 1 + tol:
                problems.append(f"{j}:{wt} fraction {x:.4f} out of [0,1]")
    for wt, cap in cluster.items():
        used = sum(alloc.get(j, {}).get(wt, 0.0) * sfs[j] for j in job_ids)
        if used > cap * (1 + tol) + tol:
            problems.append(f"{wt} capacity exceeded: {used:.3f} > {cap}")
    return problems


def check_bounds_only(alloc):
    problems = []
    if alloc is None:
        return ["allocation is None"]
    for j, per_type in alloc.items():
        for wt, x in per_type.items():
            if x < -1e-4 or x > 1 + 1e-4:
                problems.append(f"{j}:{wt} fraction {x:.4f} out of [0,1]")
    return problems


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", type=int, default=24)
    p.add_argument("--num_workers", type=int, default=16)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    failures = 0
    for policy_name in POLICIES:
        all_problems = []
        for t in range(args.trials):
            job_ids, tputs, sfs, prios, cluster = random_state(
                args.num_jobs, args.num_workers, args.seed + t)
            try:
                alloc = allocate(policy_name, tputs, sfs, prios, cluster,
                                 args.seed + t)
                if policy_name in SHARE_HINT:
                    all_problems += check_bounds_only(alloc)
                else:
                    all_problems += check(alloc, job_ids, sfs, cluster)
            except Exception as e:  # noqa: BLE001 - report, keep sweeping
                all_problems.append(f"raised {type(e).__name__}: {e}")
        status = "OK" if not all_problems else f"FAIL ({all_problems[0]})"
        print(f"{policy_name:<40} {status}")
        failures += bool(all_problems)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
