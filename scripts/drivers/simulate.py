#!/usr/bin/env python3
"""Trace-driven simulation driver.

Replays a trace against a simulated cluster and dumps the end-of-run
metrics (reference: scheduler/scripts/drivers/simulate_scheduler_with_trace.py).

Example:
    python scripts/drivers/simulate.py \
        --trace data/canonical_120job.trace \
        --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec v100:32 --round_duration 120
"""
import argparse
import json
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import driver_common  # noqa: E402
from shockwave_tpu.core.metrics import parse_cluster_spec  # noqa: E402
from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs.logconfig import LEVELS, setup_logging  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--trace", required=True)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--cluster_spec", default="v100:32",
                   help="worker_type:count[,worker_type:count...]")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--chips_per_server", type=int, default=1,
                   help="chips per simulated worker daemon (mirror a "
                        "multi-chip physical host, e.g. a gang loopback "
                        "worker)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--config", default=None,
                   help="JSON file of shockwave hyperparameters (a "
                        "'serving' block inside configures the serving "
                        "tier's autoscaler for any policy)")
    p.add_argument("--output", default=None, help="metrics pickle path")
    p.add_argument("--json_out", default=None,
                   help="also write the summary JSON line to this file "
                        "(CI artifact for the mixed serving smoke)")
    p.add_argument("--scalar_sim", action="store_true",
                   help="run the retained scalar sim core instead of the "
                        "vectorized passes (reference oracle; equivalent "
                        "to SWTPU_SCALAR_SIM=1)")
    p.add_argument("--profile_out", default=None, metavar="PSTATS",
                   help="cProfile the simulation loop (imports and trace "
                        "parsing excluded) and dump the pstats binary "
                        "here, plus a human-readable top-40 cumulative "
                        "summary at PSTATS.txt — hot-loop work should "
                        "start from this evidence (EXPERIMENTS.md "
                        "\"Fleet-scale simulation\")")
    p.add_argument("--replay_schedule", default=None, metavar="PHYSICAL_PKL",
                   help="fidelity analysis: execute this physical metric "
                        "pickle's per_round_schedule verbatim instead of "
                        "the live policy (physical-vs-replay deltas "
                        "isolate the timing model from decision "
                        "divergence)")
    p.add_argument("--measured_rates", default=None, metavar="PHYSICAL_PKL",
                   help="fidelity analysis: override each job's oracle "
                        "rate with its mean measured throughput from this "
                        "physical pickle's throughput_timeline")
    p.add_argument("--obs_trace", default=None, metavar="TRACE_JSON",
                   help="export the simulator's span trace (virtual-"
                        "clock timeline) as Chrome-trace JSON at exit")
    p.add_argument("--log_level", default=None, choices=LEVELS,
                   help="root log level (default: warning, or info "
                        "with --verbose)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    setup_logging(args.log_level
                  or ("info" if args.verbose else "warning"))

    jobs, arrival_times = parse_trace(args.trace)
    throughputs = read_throughputs(args.throughputs)
    profiles = build_profiles(jobs, throughputs)
    cluster_spec = parse_cluster_spec(args.cluster_spec)
    for wt, count in cluster_spec.items():
        if count % args.chips_per_server:
            # The scheduler registers count // chips_per_server workers, so a
            # remainder would silently simulate a smaller cluster.
            raise SystemExit(
                f"--cluster_spec {wt}:{count} is not divisible by "
                f"--chips_per_server {args.chips_per_server}")

    shockwave_config, serving_config, whatif_config, oracle_config = (
        driver_common.load_configs(args.config, args.policy, cluster_spec,
                                   args.round_duration))

    forced_schedule = None
    if args.replay_schedule:
        with open(args.replay_schedule, "rb") as f:
            forced_schedule = pickle.load(f)["per_round_schedule"]

    rate_override = None
    if args.measured_rates:
        with open(args.measured_rates, "rb") as f:
            timeline = pickle.load(f)["throughput_timeline"]
        # Mean of the per-round measured rates, skipping empty rounds
        # (a killed micro-task records 0.0).
        rate_override = {}
        for int_id, rounds in timeline.items():
            rates = [r for r, _ in rounds.values() if r > 0]
            if rates:
                rate_override[int_id] = sum(rates) / len(rates)

    sched = driver_common.build_scheduler(
        args.policy, args.throughputs, profiles,
        round_duration=args.round_duration, seed=args.seed,
        max_rounds=args.max_rounds, shockwave_config=shockwave_config,
        serving_config=serving_config, whatif_config=whatif_config,
        oracle_config=oracle_config, rate_override=rate_override,
        vectorized=not args.scalar_sim)

    profiler = None
    if args.profile_out:
        import cProfile
        profiler = cProfile.Profile()
        profiler.enable()
    sim_start = time.monotonic()
    makespan = sched.simulate(
        cluster_spec, arrival_times, jobs,
        num_chips_per_server={wt: args.chips_per_server
                              for wt in cluster_spec},
        forced_schedule=forced_schedule)
    sim_wall_s = time.monotonic() - sim_start
    if profiler is not None:
        profiler.disable()
        profiler.dump_stats(args.profile_out)
        import io
        import pstats
        buf = io.StringIO()
        pstats.Stats(profiler, stream=buf).sort_stats(
            "cumulative").print_stats(40)
        # Telemetry dump, not durable state: a torn file just re-runs.
        with open(args.profile_out + ".txt", "w") as f:
            f.write(buf.getvalue())
        print(f"profile: {args.profile_out} (summary: "
              f"{args.profile_out}.txt)", file=sys.stderr)

    metrics = {"trace_file": args.trace,
               **driver_common.collect_metrics(sched, makespan,
                                               args.round_duration,
                                               args.policy)}

    summary = driver_common.summary_core(metrics, sched)
    milp = driver_common.milp_summary(metrics["milp_solve_stats"])
    summary.update(milp)
    # Wall split: the sim core (vectorized per-round bookkeeping) vs the
    # MILP solver chain — the bench trajectory tracks both.
    summary["sim_wall_s"] = round(sim_wall_s, 2)
    summary["milp_wall_s"] = milp.get("milp_wall_s", 0.0)
    summary["sim_core_wall_s"] = round(
        sim_wall_s - milp.get("milp_wall_s", 0.0), 2)
    print(json.dumps(summary))
    if args.json_out:
        # CI artifact, not durable state: a torn file just re-runs.
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=2)

    if args.output:
        with open(args.output, "wb") as f:
            pickle.dump(metrics, f)
    if args.obs_trace:
        sched.obs.tracer.export_chrome_trace(args.obs_trace)


if __name__ == "__main__":
    main()
