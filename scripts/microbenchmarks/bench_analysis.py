#!/usr/bin/env python3
"""Analyzer-performance microbenchmark: whole-tree swtpu-check wall.

Measures three things the analyzer-performance satellite cares about:

- **cold** — one full analyzer run from scratch (parse every module,
  build the shared call graph, run every pass + the suppression
  audit): what CI pays;
- **warm** — a second run against the process-wide cached RepoIndex
  (mtime-validated): what repeated in-process runs (the tier-1 gate's
  three CLI invocations, editor integrations) pay;
- **per-pass** — the wall table from ``run_timed``, so a regression is
  attributable to one pass rather than "the analyzer got slow".

Prints ONE JSON line. ``--smoke`` exits nonzero when the cold wall
exceeds ``--max_cold_s`` or the warm wall exceeds ``--max_warm_s`` —
the CI floor keeping whole-tree analysis cheap enough to run on every
push (the race detector alone must stay well under a second on this
~180-module tree).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.analysis import __main__ as cli  # noqa: E402
from shockwave_tpu.analysis import core  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--root", default=None,
                   help="repo root (default: autodetect)")
    p.add_argument("--runs", type=int, default=3,
                   help="warm runs to average")
    p.add_argument("--smoke", action="store_true",
                   help="exit 1 when a floor is violated")
    p.add_argument("--max_cold_s", type=float, default=6.0,
                   help="cold full-run ceiling (parse + graph + passes)")
    p.add_argument("--max_warm_s", type=float, default=3.0,
                   help="warm (cached-index) full-run ceiling")
    p.add_argument("--max_lockflow_warm_s", type=float, default=0.5,
                   help="warm per-pass ceiling for EACH of the "
                        "deadlock and hold-discipline passes (the "
                        "shared lockflow dataflow is memoized on the "
                        "index, so warm reruns must be re-derivation "
                        "cost only)")
    p.add_argument("--output", default=None,
                   help="also write the JSON record here")
    args = p.parse_args()

    root = args.root or cli.default_root()

    # Cold: empty the cache so parsing + call-graph cost is included.
    core._INDEX_CACHE.clear()
    t0 = time.perf_counter()
    findings, timing = cli.run_timed(root=root)
    cold_s = time.perf_counter() - t0

    warm_walls = []
    for _ in range(max(args.runs, 1)):
        t0 = time.perf_counter()
        findings, timing = cli.run_timed(root=root)
        warm_walls.append(time.perf_counter() - t0)
    warm_s = min(warm_walls)

    record = {
        "bench": "analysis",
        "files_indexed": len(core.cached_index(
            root, include_dirs=cli.DEFAULT_INCLUDE_DIRS,
            exclude_globs=cli.DEFAULT_EXCLUDE_GLOBS).files),
        "findings": len(findings),
        "cold_wall_s": round(cold_s, 4),
        "warm_wall_s": round(warm_s, 4),
        "per_pass_wall_s": {name: t["wall_s"]
                            for name, t in sorted(timing.items())},
    }
    line = json.dumps(record, sort_keys=True)
    print(line)
    if args.output:
        with open(args.output, "w") as f:
            f.write(line + "\n")

    if args.smoke:
        failures = []
        if cold_s > args.max_cold_s:
            failures.append(f"cold wall {cold_s:.2f}s > "
                            f"{args.max_cold_s}s")
        if warm_s > args.max_warm_s:
            failures.append(f"warm wall {warm_s:.2f}s > "
                            f"{args.max_warm_s}s")
        # timing holds the LAST (warm) run's per-pass walls.
        for lockflow_pass in ("deadlock", "hold-discipline"):
            wall = timing.get(lockflow_pass, {}).get("wall_s", 0.0)
            if wall > args.max_lockflow_warm_s:
                failures.append(
                    f"{lockflow_pass} warm wall {wall:.3f}s > "
                    f"{args.max_lockflow_warm_s}s")
        if findings:
            failures.append(f"{len(findings)} unexpected finding(s)")
        if failures:
            print("bench_analysis SMOKE FAIL: " + "; ".join(failures),
                  file=sys.stderr)
            return 1
        print(f"bench_analysis smoke ok: cold {cold_s:.2f}s, "
              f"warm {warm_s:.2f}s over "
              f"{record['files_indexed']} files", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
