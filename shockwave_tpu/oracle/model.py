"""The learned throughput model: seeded ridge regression in log space
plus online residual corrections.

Offline fit (`ThroughputModel.fit`): closed-form normal equations over
the featurized history rows (`features.featurize`), predicting
``log(steps/s)`` — pure numpy float64, no iterative solver, so two fits
of the same rows are bit-identical and the saved JSON artifact is
byte-stable (sorted keys, 12-significant-digit floats).

Online refinement (`observe`): as Done reports stream in, the residual
``log(observed) - log(fit)`` is EMA-tracked per exact
``(family, batch_size, scale_factor, worker_type)`` key and applied
multiplicatively on top of the fit — the planner's view converges to
the measured rate without refitting mid-run.

Every prediction carries a confidence in [0, 1) from the evidence
behind it: online-corrected exact keys count most, fit-time rows for
the same (family, worker_type) next, same-family rows on *other* worker
types least (those predictions lean on the per-type intercept and the
per-generation comm-scaling term — the heterogeneous-cluster
extrapolation path). The chain in `core/throughput_estimator.py` gates
planner trust on it.
"""
from __future__ import annotations

import json
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .features import (family_of, feature_dim, featurize, generation_of)

MODEL_SCHEMA = 1

#: Default L2 regularizer for the normal equations.
DEFAULT_RIDGE = 1e-3

#: Default EMA weight for online residual corrections.
DEFAULT_ONLINE_ALPHA = 0.5

#: Residual clamp: one wild measurement (a stalled gang, a clock skew)
#: must not swing a correction by more than e^3 ~ 20x.
RESIDUAL_CLAMP = 3.0

#: Confidence evidence weights: exact online observations / fit rows on
#: the same (family, worker_type) / same-family rows elsewhere (the
#: cross-generation extrapolation path).
_W_EXACT, _W_TYPE, _W_FAMILY = 4.0, 1.0, 0.5
_CONF_HALF = 4.0

_RATE_FLOOR, _RATE_CEIL = 1e-6, 1e9


def _round12(x: float) -> float:
    """Round to 12 significant digits: stable under JSON round-trip,
    far above any physical measurement precision."""
    return float(f"{float(x):.12g}")


def _corr_key(family: str, batch_size, scale_factor: int,
              worker_type: str) -> str:
    return f"{family}|{batch_size}|{int(scale_factor)}|{worker_type}"


class ThroughputModel:
    """Featurized log-throughput regression with online corrections."""

    def __init__(self, seed: int = 0, ridge: float = DEFAULT_RIDGE,
                 families: Optional[List[str]] = None,
                 worker_types: Optional[List[str]] = None,
                 generations: Optional[List[str]] = None,
                 weights: Optional[Sequence[float]] = None,
                 rmse: float = 0.0, n_rows: int = 0,
                 support: Optional[Dict[str, Dict[str, int]]] = None,
                 corrections: Optional[Dict[str, List[float]]] = None):
        self.seed = int(seed)
        self.ridge = float(ridge)
        self.families = list(families or [])
        self.worker_types = list(worker_types or [])
        self.generations = list(generations or [])
        dim = feature_dim(self.families, self.worker_types,
                          self.generations)
        self.weights = (np.asarray(weights, dtype=np.float64)
                        if weights is not None
                        else np.zeros(dim, dtype=np.float64))
        if self.weights.shape != (dim,):
            raise ValueError(
                f"weight vector has dim {self.weights.shape}, vocab "
                f"implies {dim}")
        self.rmse = float(rmse)
        self.n_rows = int(n_rows)
        #: family -> worker_type -> fit-row count.
        self.support: Dict[str, Dict[str, int]] = {
            f: dict(by_wt) for f, by_wt in (support or {}).items()}
        #: exact-key -> [log-residual EMA, observation count].
        self.corrections: Dict[str, List[float]] = {
            k: [float(v[0]), int(v[1])]
            for k, v in (corrections or {}).items()}

    # -- fitting --------------------------------------------------------

    @classmethod
    def fit(cls, rows: Sequence[tuple], seed: int = 0,
            ridge: float = DEFAULT_RIDGE) -> "ThroughputModel":
        """Fit from ``(job_type, batch_size, scale_factor, worker_type,
        steps_per_s)`` rows (rates <= 0 are dropped)."""
        clean = [r for r in rows if float(r[4]) > 0.0]
        if not clean:
            raise ValueError("no positive-rate training rows")
        families = sorted({family_of(str(r[0])) for r in clean})
        worker_types = sorted({str(r[3]) for r in clean})
        generations = sorted({generation_of(wt) for wt in worker_types})
        dim = feature_dim(families, worker_types, generations)
        X = np.empty((len(clean), dim), dtype=np.float64)
        y = np.empty(len(clean), dtype=np.float64)
        support: Dict[str, Dict[str, int]] = {}
        for i, (job_type, bs, sf, wt, rate) in enumerate(clean):
            X[i] = featurize(str(job_type), bs, int(sf), str(wt),
                             families, worker_types, generations, seed)
            y[i] = math.log(float(rate))
            fam = family_of(str(job_type))
            by_wt = support.setdefault(fam, {})
            by_wt[str(wt)] = by_wt.get(str(wt), 0) + 1
        A = X.T @ X + float(ridge) * np.eye(dim)
        w = np.linalg.solve(A, X.T @ y)
        # Round the solved weights once so save/load and a fresh fit
        # agree bitwise (linalg noise below 1e-12 relative is dropped).
        w = np.array([_round12(v) for v in w], dtype=np.float64)
        rmse = _round12(math.sqrt(float(np.mean((X @ w - y) ** 2))))
        return cls(seed=seed, ridge=ridge, families=families,
                   worker_types=worker_types, generations=generations,
                   weights=w, rmse=rmse, n_rows=len(clean),
                   support=support)

    # -- prediction -----------------------------------------------------

    def _base(self, job_type: str, batch_size, scale_factor: int,
              worker_type: str) -> float:
        x = featurize(job_type, batch_size, int(scale_factor),
                      worker_type, self.families, self.worker_types,
                      self.generations, self.seed)
        return float(np.clip(math.exp(float(x @ self.weights)),
                             _RATE_FLOOR, _RATE_CEIL))

    def predict(self, job_type: str, batch_size, scale_factor: int,
                worker_type: str) -> Tuple[float, float]:
        """(steps_per_s, confidence)."""
        fam = family_of(job_type)
        key = _corr_key(fam, batch_size, scale_factor, worker_type)
        rate = self._base(job_type, batch_size, scale_factor,
                          worker_type)
        corr = self.corrections.get(key)
        n_exact = 0
        if corr is not None:
            rate = float(np.clip(rate * math.exp(corr[0]),
                                 _RATE_FLOOR, _RATE_CEIL))
            n_exact = int(corr[1])
        by_wt = self.support.get(fam, {})
        n_type = by_wt.get(worker_type, 0)
        n_family = sum(by_wt.values())
        evidence = _W_EXACT * n_exact
        if fam in self.families:
            evidence += (_W_TYPE * n_type
                         + _W_FAMILY * max(n_family - n_type, 0))
        confidence = round(evidence / (evidence + _CONF_HALF), 6)
        return rate, confidence

    def family_samples(self, job_type: str) -> int:
        """Total evidence rows behind this family: fit rows plus online
        observations (the serving mu prior's zero-sample gate)."""
        fam = family_of(job_type)
        fit_rows = sum(self.support.get(fam, {}).values())
        online = sum(int(v[1]) for k, v in self.corrections.items()
                     if k.split("|", 1)[0] == fam)
        return fit_rows + online

    # -- online refinement ----------------------------------------------

    def observe(self, job_type: str, batch_size, scale_factor: int,
                worker_type: str, steps_per_s: float,
                alpha: float = DEFAULT_ONLINE_ALPHA) -> None:
        """Fold one observed rate into the exact-key residual EMA."""
        rate = float(steps_per_s)
        if rate <= 0.0:
            return
        base = self._base(job_type, batch_size, scale_factor,
                          worker_type)
        residual = max(-RESIDUAL_CLAMP,
                       min(RESIDUAL_CLAMP, math.log(rate / base)))
        fam = family_of(job_type)
        key = _corr_key(fam, batch_size, scale_factor, worker_type)
        prev = self.corrections.get(key)
        if prev is None:
            self.corrections[key] = [residual, 1]
        else:
            prev[0] = (1.0 - alpha) * prev[0] + alpha * residual
            prev[1] = int(prev[1]) + 1

    # -- serialization --------------------------------------------------

    def to_payload(self) -> dict:
        return {
            "schema": MODEL_SCHEMA,
            "seed": self.seed,
            "ridge": _round12(self.ridge),
            "families": list(self.families),
            "worker_types": list(self.worker_types),
            "generations": list(self.generations),
            "weights": [_round12(v) for v in self.weights],
            "rmse": _round12(self.rmse),
            "n_rows": self.n_rows,
            "support": {f: {wt: int(n) for wt, n in by_wt.items()}
                        for f, by_wt in self.support.items()},
            "corrections": {k: [_round12(v[0]), int(v[1])]
                            for k, v in self.corrections.items()},
        }

    def save(self, path: str) -> None:
        text = json.dumps(self.to_payload(), sort_keys=True, indent=2)
        with open(path, "w", encoding="utf-8") as f:
            f.write(text + "\n")

    @classmethod
    def from_payload(cls, payload: dict) -> "ThroughputModel":
        if payload.get("schema") != MODEL_SCHEMA:
            raise ValueError(
                f"model schema {payload.get('schema')!r} unsupported "
                f"(this build reads {MODEL_SCHEMA})")
        return cls(seed=payload.get("seed", 0),
                   ridge=payload.get("ridge", DEFAULT_RIDGE),
                   families=payload.get("families", []),
                   worker_types=payload.get("worker_types", []),
                   generations=payload.get("generations", []),
                   weights=payload.get("weights"),
                   rmse=payload.get("rmse", 0.0),
                   n_rows=payload.get("n_rows", 0),
                   support=payload.get("support", {}),
                   corrections=payload.get("corrections", {}))

    @classmethod
    def load(cls, path: str) -> "ThroughputModel":
        with open(path, encoding="utf-8") as f:
            return cls.from_payload(json.load(f))
