"""Minimal REAL trainer for loopback drives: a jax-free training loop
under the genuine LeaseIterator, launched by the genuine Dispatcher as
a subprocess — so tests can assert the whole fleet-trace chain
(scheduler -> worker daemon -> trainer) across real process boundaries.

Consumes the dispatcher-constructed command line
(``--local_rank N --num_steps N --checkpoint_dir D
--enable_lease_iterator``) plus ``--step_time`` (simulated per-step
compute) and ``--chunk`` (steps per dispatch before a clean exit, for
deterministic drives; 0 runs to lease expiry).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from shockwave_tpu.runtime.iterator import LeaseIterator  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--local_rank", type=int, default=0)
    p.add_argument("--num_steps", type=int, required=True)
    p.add_argument("--checkpoint_dir", required=True)
    p.add_argument("--enable_lease_iterator", action="store_true")
    p.add_argument("--step_time", type=float, default=0.001)
    p.add_argument("--chunk", type=int, default=0,
                   help="steps per dispatch before a clean exit "
                        "(0 = run until the lease expires)")
    p.add_argument("--batch_size", type=int, default=32)
    args = p.parse_args()

    state = {"restored": False}

    def load_checkpoint(path):
        state["restored"] = os.path.exists(os.path.join(path, "step"))
        return state["restored"]

    def save_checkpoint(path, step):
        with open(os.path.join(path, "step"), "w") as f:
            f.write(str(step))

    it = LeaseIterator(
        data_loader=list(range(64)), checkpoint_dir=args.checkpoint_dir,
        load_checkpoint_func=load_checkpoint,
        save_checkpoint_func=save_checkpoint, synthetic_data=True)
    it.load_checkpoint(args.checkpoint_dir)

    steps = 0
    while not it.done and (args.chunk <= 0 or steps < args.chunk):
        try:
            for _ in it:
                steps += 1
                time.sleep(args.step_time)
                if args.chunk > 0 and steps >= args.chunk:
                    break
        except StopIteration:
            pass
    if not it.done:
        it.complete()
    it.save_checkpoint(args.checkpoint_dir, steps)


if __name__ == "__main__":
    main()
